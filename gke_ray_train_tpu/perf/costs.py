"""Compile-level step cost accounting — numbers that need no accelerator.

``StepCostReport`` extracts XLA's own ledger from an AOT-compiled
executable: ``cost_analysis()`` (flops, bytes accessed),
``memory_analysis()`` (peak temp / argument / output / aliased bytes)
and the optimized HLO text (collective count & bytes by kind). All of
it comes from lowering + compilation alone, so the identical report is
produced on the 8-fake-device CPU mesh CI runs on and on a v5e-16 —
which is what makes the budget harness (:mod:`perf.budget`) a tier-1
regression gate rather than a hardware benchmark.

Numbers describe the **per-device SPMD program** XLA compiled (under
GSPMD the compiled module is the per-device partition; flops/bytes are
that partition's). The analytic MFU ceiling is the classic roofline:
``t_compute = flops / peak_flops``, ``t_hbm = bytes / hbm_bw``, ceiling
= ``t_compute / max(t_compute, t_hbm)`` at a given chip spec.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Tuple

import jax


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    name: str
    peak_flops: float        # dense bf16 FLOP/s per chip
    hbm_bytes_per_s: float   # HBM bandwidth per chip
    hbm_bytes: float         # HBM capacity per chip
    vmem_bytes: float = 16 * 2**20   # on-chip vector memory per core
    # network: intra-slice ICI vs the inter-slice data-center fabric.
    # Nominal per-chip figures (order-of-magnitude, like the cpu spec's
    # flops) — what matters for the roofline is the RATIO: DCN is
    # ~1.5 orders of magnitude slower than ICI, which is why a flat
    # all-reduce whose full payload crosses slices dominates step time
    # on multi-slice pools and why hier_psum sends 1/ici_size of it.
    ici_bytes_per_s: float = 100e9
    dcn_bytes_per_s: float = 6.25e9   # ~50 Gbit/s per chip share


CHIP_SPECS = {
    "v5e": ChipSpec("v5e", 197e12, 819e9, 16 * 2**30,
                    ici_bytes_per_s=200e9),
    "v5p": ChipSpec("v5p", 459e12, 2765e9, 95 * 2**30,
                    ici_bytes_per_s=600e9),
    "v4": ChipSpec("v4", 275e12, 1228e9, 32 * 2**30,
                   ici_bytes_per_s=300e9),
    "v6e": ChipSpec("v6e", 918e12, 1640e9, 32 * 2**30,
                    ici_bytes_per_s=400e9),
    # nominal CPU spec: keeps ceilings finite for the CI mesh; vmem uses
    # the TPU figure so kernelcheck KER002 verdicts match real chips
    "cpu": ChipSpec("cpu", 1e12, 50e9, 8 * 2**30,
                    ici_bytes_per_s=10e9, dcn_bytes_per_s=1e9),
}

# device_kind substring → spec key (same matching discipline as
# train.metrics.PEAK_FLOPS; longest key wins)
_KIND_TO_SPEC = {
    "v5 lite": "v5e", "v5e": "v5e", "v5p": "v5p", "v5": "v5p",
    "v4": "v4", "v6 lite": "v6e", "v6e": "v6e", "cpu": "cpu",
}


def chip_spec_for_devices(default: str = "v5e") -> ChipSpec:
    kind = jax.devices()[0].device_kind.lower()
    for k, spec in sorted(_KIND_TO_SPEC.items(), key=lambda kv: -len(kv[0])):
        if k in kind:
            return CHIP_SPECS[spec]
    return CHIP_SPECS[default]


COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "all-to-all", "collective-permute")

_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
                "f16": 2, "bf16": 2, "s32": 4, "u32": 4, "f32": 4,
                "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16}
_SHAPE_RE = re.compile(r"\b(pred|bf16|f16|f32|f64|s8|u8|s16|u16|s32|u32"
                       r"|s64|u64|c64|c128)\[([0-9,]*)\]")
# "<result-type> <kind>(" — also matches async "-start" forms; "-done"
# deliberately does not match (it would double-count the async pair)
_COLL_RE = re.compile(
    r"=\s*(.*?)\s(" + "|".join(COLLECTIVE_KINDS) + r")(?:-start)?\(")


def _shape_bytes(type_str: str) -> int:
    total = 0
    for dtype, dims in _SHAPE_RE.findall(type_str):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


# ---------------------------------------------------------------------------
# while-loop trip counts: a collective inside a scan body appears ONCE
# in the HLO text but executes once PER TRIP — static byte accounting
# that ignores the multiplier under-reports a grad-accum or layer-scan
# program by the scan length (the PR-11 caveat, fixed here)
# ---------------------------------------------------------------------------

# a while op names its body computation and (XLA's loop analysis
# willing) its statically-known trip count in backend_config. A while
# can be the computation ROOT (a step whose entry or outer body
# returns only the scan carry) — the prefix must not hide it.
_WHILE_RE = re.compile(r"(?:ROOT\s+)?%[\w.\-]+ = [^\n]*?\bwhile\([^\n]*")
_WHILE_BODY_RE = re.compile(r"body=%([\w.\-]+)")
_TRIP_RE = re.compile(r'known_trip_count[^0-9]*(\d+)')


def _while_trip_counts(hlo_text: str,
                       comps: Optional[List[Tuple[str, List[str]]]] = None
                       ) -> Dict[str, int]:
    """computation name -> executions per step, for every while BODY
    whose trip count XLA proved statically (``known_trip_count``; a
    ``compare(iv, constant), direction=LT`` condition is the fallback).
    Nested loops compose: a body inside a body multiplies through. An
    unknown trip count conservatively counts once (the pre-fix
    behavior), never guesses. ``comps``: a precomputed
    :func:`_computation_lines` split (``step_cost_report`` parses the
    — potentially multi-MB — HLO text once and shares it)."""
    if comps is None:
        comps = _computation_lines(hlo_text)
    # (containing computation, body name, trips) per while op
    whiles: List[Tuple[str, str, Optional[int]]] = []
    cond_of: Dict[str, str] = {}
    for comp, comp_lines in comps:
        for line in comp_lines:
            s = line.strip()
            if not _WHILE_RE.match(s):
                continue
            bm = _WHILE_BODY_RE.search(s)
            if bm is None:
                continue
            tm = _TRIP_RE.search(s)
            trips = int(tm.group(1)) if tm else None
            whiles.append((comp, bm.group(1), trips))
            cm = re.search(r"condition=%([\w.\-]+)", s)
            if cm:
                cond_of[bm.group(1)] = cm.group(1)
    if not whiles:
        return {}
    # fallback trip parse: the condition computation's
    # `compare(iv, constant(N)), direction=LT` — the lax.scan shape
    unresolved = [b for _, b, t in whiles if t is None]
    cond_trips: Dict[str, int] = {}
    if unresolved:
        consts: Dict[str, Dict[str, int]] = {}
        lt: Dict[str, List[str]] = {}
        for comp, comp_lines in comps:
            for line in comp_lines:
                s = line.strip()
                cm = re.match(r"(?:ROOT\s+)?%([\w.\-]+) = s32\[\] "
                              r"constant\((\d+)\)", s)
                if cm:
                    consts.setdefault(comp, {})[cm.group(1)] = \
                        int(cm.group(2))
                if "direction=LT" in s and " compare(" in s:
                    lt.setdefault(comp, []).extend(
                        re.findall(r"%([\w.\-]+)", s))
        for body, cond in cond_of.items():
            operands = lt.get(cond, ())
            vals = [consts.get(cond, {}).get(o) for o in operands]
            vals = [v for v in vals if v is not None]
            if len(vals) == 1:
                cond_trips[body] = vals[0]
    # compose nesting: multiplier(body) = trips x multiplier(container)
    mult: Dict[str, int] = {}
    trips_of = {b: (t if t is not None else cond_trips.get(b))
                for _, b, t in whiles}
    container = {b: c for c, b, _ in whiles}
    for body in trips_of:
        m, seen, b = 1, set(), body
        while b in trips_of and b not in seen:
            seen.add(b)
            t = trips_of[b]
            if t is None:
                break
            m *= t
            b = container[b]
        mult[body] = m
    return {b: m for b, m in mult.items() if m > 1}


# ---------------------------------------------------------------------------
# replica-group parsing: which DEVICES a collective spans — the input
# to the ICI/DCN byte attribution (a group that crosses a slice
# boundary pays data-center-network latency, not ICI)
# ---------------------------------------------------------------------------

_RG_IOTA_RE = re.compile(
    r"replica_groups=\[([0-9,]+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?")
_PAIRS_RE = re.compile(r"source_target_pairs=\{([0-9,{} ]*)\}")


def _replica_groups(line: str) -> Optional[List[List[int]]]:
    """Parse an HLO collective line's replica groups. Handles the
    explicit ``{{0,1},{2,3}}`` form, the iota ``[2,4]<=[8]`` (optionally
    ``T(perm)``-transposed) form, and collective-permute's
    ``source_target_pairs``. Returns None when the line carries no
    group syntax at all; ``[[]]`` (one empty group) means "all
    devices" per HLO semantics."""
    m = _RG_IOTA_RE.search(line)
    if m:
        dims = [int(d) for d in m.group(1).split(",")]
        reshape = [int(d) for d in m.group(2).split(",")]
        total = 1
        for d in reshape:
            total *= d
        ids = list(range(total))
        if m.group(3):
            import numpy as np
            perm = [int(p) for p in m.group(3).split(",")]
            ids = list(np.arange(total).reshape(reshape)
                       .transpose(perm).flatten())
        group_size = 1
        for d in dims[1:]:
            group_size *= d
        return [list(map(int, ids[i:i + group_size]))
                for i in range(0, total, group_size)]
    m = re.search(r"replica_groups=\{((?:\{[0-9, ]*\},?)*)\}", line)
    if m is not None:
        groups = [[int(x) for x in g.split(",") if x.strip()]
                  for g in re.findall(r"\{([0-9, ]*)\}", m.group(1))]
        if groups:
            return groups
        return [[]]   # replica_groups={} = one group of every device
    m = _PAIRS_RE.search(line)
    if m is not None:
        return [[int(x) for x in g.split(",") if x.strip()]
                for g in re.findall(r"\{([0-9, ]*)\}", m.group(1))]
    return None


def _crosses_slices(groups: Optional[List[List[int]]],
                    slice_map: List[int]) -> bool:
    """Does any replica group span more than one slice? Group ids are
    positions in the program's device assignment; for the hybrid mesh
    contract (slices are the OUTERMOST, contiguous blocks of the
    flattened mesh — both ``create_hybrid_device_mesh`` and the
    emulated fake-device layout, pinned in test_mesh.py) that position
    maps to a slice via ``slice_map``."""
    if not slice_map or len(set(slice_map)) <= 1:
        return False
    if not groups:
        return False
    for g in groups:
        members = g if g else range(len(slice_map))
        seen = {slice_map[i] for i in members if i < len(slice_map)}
        if len(seen) > 1:
            return True
    return False


def collective_stats(hlo_text: str, *,
                     _comps=None, _trips=None
                     ) -> Tuple[Dict[str, int], int, List[str]]:
    """(count-by-kind, total result bytes, matched HLO lines) for every
    collective in an optimized HLO module. The lines ride along so a
    budget miss can print the actual offending ops, not just a count.

    Bytes are weighted by the statically-known while-loop trip count of
    the computation the op sits in (a collective in a 2-layer scan body
    executes twice per step); COUNTS stay static op counts — the
    exact-count check is about program structure, the byte ledger about
    runtime traffic. Trip-weighted lines carry an ``// x<N>`` suffix.

    ``_comps``/``_trips``: precomputed computation split / trip map —
    ``step_cost_report`` parses the HLO text once and shares it with
    all three analyses (a real-model scheduled dump is multi-MB)."""
    counts = {k: 0 for k in COLLECTIVE_KINDS}
    total_bytes = 0
    lines: List[str] = []
    comps = _comps if _comps is not None else _computation_lines(hlo_text)
    trips = _trips if _trips is not None \
        else _while_trip_counts(hlo_text, comps)
    for comp, comp_lines in comps:
        mult = trips.get(comp, 1)
        for line in comp_lines:
            m = _COLL_RE.search(line)
            if m is None:
                continue
            counts[m.group(2)] += 1
            total_bytes += _shape_bytes(m.group(1)) * mult
            tag = f" // x{mult} while-trip" if mult > 1 else ""
            lines.append(line.strip()[:200] + tag)
    return counts, total_bytes, lines


def collective_axis_stats(hlo_text: str, slice_map: List[int], *,
                          _comps=None, _trips=None
                          ) -> Tuple[int, int, List[str]]:
    """(ici_bytes, dcn_bytes, dcn attribution lines): every
    collective's result bytes attributed to the interconnect its
    replica groups span — intra-slice ICI, or DCN when a group crosses
    the slice boundary. Trip-weighted like :func:`collective_stats`
    (and sharing its precomputed-parse convention). With a
    single-slice (or empty) ``slice_map`` everything is ICI by
    construction."""
    ici = 0
    dcn = 0
    lines: List[str] = []
    comps = _comps if _comps is not None else _computation_lines(hlo_text)
    trips = _trips if _trips is not None \
        else _while_trip_counts(hlo_text, comps)
    for comp, comp_lines in comps:
        mult = trips.get(comp, 1)
        for line in comp_lines:
            m = _COLL_RE.search(line)
            if m is None:
                continue
            nbytes = _shape_bytes(m.group(1)) * mult
            groups = _replica_groups(line)
            if _crosses_slices(groups, slice_map):
                dcn += nbytes
                n_slices = len(set(slice_map))
                lines.append(
                    f"{m.group(2)} {nbytes}B crosses the slice boundary "
                    f"(replica groups span {n_slices} slices"
                    + (f"; x{mult} while-trip" if mult > 1 else "")
                    + "): " + line.strip()[:140])
            else:
                ici += nbytes
    return ici, dcn, lines


def _computation_lines(hlo_text: str) -> List[Tuple[str, List[str]]]:
    """(computation name, raw op lines) per computation, in file
    order — the shared walk collective_stats / collective_axis_stats
    attribute trip counts through. Lines outside any computation
    header land in an implicit ``""`` fragment (multiplier 1), so bare
    HLO snippets — unit-test fixtures — still parse."""
    out: List[Tuple[str, List[str]]] = []
    cur: List[str] = []
    name = ""
    in_comp = False
    for line in hlo_text.splitlines():
        s = line.strip()
        if not in_comp:
            if s.endswith("{") and ("->" in s or s.startswith("ENTRY")):
                if cur:
                    out.append((name, cur))
                m = re.match(r"(?:ENTRY\s+)?%([\w.\-]+)", s)
                name = m.group(1) if m else "?"
                cur = []
                in_comp = True
            else:
                cur.append(line)
            continue
        if s == "}" or line.startswith("}"):
            out.append((name, cur))
            cur = []
            name = ""
            in_comp = False
            continue
        cur.append(line)
    if cur:
        out.append((name, cur))
    return out


# ---------------------------------------------------------------------------
# overlap / exposure analysis of the scheduled entry computation
# ---------------------------------------------------------------------------

_ENTRY_OP_RE = re.compile(r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*(.+)$")
_COMPUTE_KINDS = ("dot", "convolution", "fusion", "custom-call")
_COMPUTE_RE = re.compile(
    r"^(.*?)\s(" + "|".join(_COMPUTE_KINDS) + r")\(")


def _computations(hlo_text: str
                  ) -> List[Tuple[str, List[Tuple[str, str]], bool]]:
    """Per-computation (comp_name, [(name, rhs)], is_entry) triples, in
    schedule order (the optimized module prints each computation's ops
    in the order the scheduler chose). Collectives live in the ENTRY
    computation AND in loop bodies (a scanned grad-accum step keeps its
    collectives inside the while body), so exposure is analyzed per
    computation — and the carried-to-root classification needs to know
    which root is a LOOP carry vs the program output."""
    comps: List[Tuple[str, List[Tuple[str, str]], bool]] = []
    cur: Optional[List[Tuple[str, str]]] = None
    comp_name = ""
    is_entry = False
    for line in hlo_text.splitlines():
        stripped = line.strip()
        if cur is None:
            # "%comp (args) -> type {" or "ENTRY %main (...) -> ... {"
            if stripped.endswith("{") and ("->" in stripped
                                           or stripped.startswith("ENTRY")):
                cur = []
                is_entry = stripped.startswith("ENTRY")
                m = re.match(r"(?:ENTRY\s+)?%([\w.\-]+)", stripped)
                comp_name = m.group(1) if m else "?"
            continue
        if stripped == "}" or line.startswith("}"):
            comps.append((comp_name, cur, is_entry))
            cur = None
            continue
        m = _ENTRY_OP_RE.match(line)
        if m:
            cur.append((m.group(1), m.group(2)))
    if cur:
        comps.append((comp_name, cur, is_entry))
    return comps


def overlap_stats(hlo_text: str, *,
                  _trips=None) -> Tuple[int, float, List[str]]:
    """(exposed_collective_bytes, overlap_frac, attribution lines).

    Walks the scheduled computations and classifies every collective as
    *hidden* or *EXPOSED*. Three ways to be hidden, all bytes-weighted
    (a window must hold at least the collective's own result bytes of
    independent compute — a 1-op window cannot mask a multi-MB
    all-gather):

    - an async ``-start``/``-done`` pair with enough independent
      compute scheduled inside the window;
    - a synchronous collective *scheduled ahead of its first consumer*
      with enough independent compute in the gap (the latency-hiding
      schedule already moved it — dataflow through copies / bitcasts /
      tuples / opt-barriers is resolved, so a fence does not count as
      a consumer);
    - a synchronous collective whose result is consumed only by the
      NEXT loop iteration (it flows to the while body's root tuple —
      the double-buffered prefetch shape ``train/overlap.py`` emits:
      layer *k+1*'s all-gather is issued while layer *k* computes, so
      the whole body's independent compute is available to hide it.
      The CPU list scheduler shows no async pair, but the *dataflow*
      is schedule-independent — an async runtime (TPU DMA engines,
      XLA's latency-hiding scheduler) overlaps a carried collective by
      construction, which is what lets CPU-mesh budgets assert the
      overlap claim while the accelerator backend is dark).

    Everything else is EXPOSED (the step stalls for the full fabric
    latency); the attribution line reports ``hidden_compute_bytes`` —
    the independent compute (neither ancestor nor descendant) a
    latency-hiding schedule COULD move into its window. That number is
    the actionable half: ``exposed > 0`` with independent compute
    available is exactly the overlap opportunity ROADMAP #3 asserts
    through budgets.

    ``overlap_frac`` = hidden bytes / total collective bytes (1.0 when
    the program has no collectives — nothing is exposed). Both sides
    are weighted by the statically-known while-trip count of the
    computation (a collective in a 2-trip scan body is executed — and
    exposed or hidden — twice per step; scaling exposed and total
    together keeps the frac a per-execution property)."""
    exposed = 0
    total = 0
    lines: List[str] = []
    trips = _trips if _trips is not None else _while_trip_counts(hlo_text)
    for comp_name, ops, is_entry in _computations(hlo_text):
        e, t, ls = _overlap_in_computation(ops, is_entry=is_entry)
        mult = trips.get(comp_name, 1)
        exposed += e * mult
        total += t * mult
        lines.extend(ls if mult == 1
                     else [f"{ln} // x{mult} while-trip" for ln in ls])
    frac = 1.0 if total == 0 else round(1.0 - exposed / total, 6)
    return exposed, frac, lines


# ops that move/regroup data without computing: dataflow is resolved
# THROUGH them when finding a collective's real consumers (a copy or a
# scheduling fence between a prefetched all-gather and the loop root
# must not read as "consumed immediately")
_PASSTHROUGH_KINDS = frozenset({
    "copy", "bitcast", "tuple", "get-tuple-element", "opt-barrier",
    "optimization-barrier"})
_ROOT = "#root"   # sentinel consumer: the computation's root tuple


def _overlap_in_computation(ops: List[Tuple[str, str]], *,
                            is_entry: bool = False
                            ) -> Tuple[int, int, List[str]]:
    index = {name: i for i, (name, _) in enumerate(ops)}
    deps: Dict[str, List[str]] = {}
    users: Dict[str, List[str]] = {n: [] for n, _ in ops}
    kind_of: Dict[str, str] = {}
    for name, rhs in ops:
        # the opcode is the first WHITESPACE-PRECEDED word directly
        # followed by "(" — result types never contain one, a
        # tuple-typed result's leading "(f32[...], ...)" holds no such
        # pair, and TPU tile-layout annotations ("{1,0:T(8,128)}")
        # prepend ":" not whitespace, so they can't shadow the opcode
        km = re.search(r"(?<=\s)([\w\-]+)\(", rhs)
        kind_of[name] = km.group(1) if km else ""
        paren = rhs.find(" " + kind_of[name] + "(") if km else -1
        body = rhs[paren:] if paren >= 0 else rhs
        deps[name] = [d for d in re.findall(r"%([\w.\-]+)", body)
                      if d in index and d != name]
        for d in deps[name]:
            users[d].append(name)
    root = ops[-1][0] if ops else None

    def reach(name: str, edges: Dict[str, List[str]]) -> set:
        """Transitive closure from ONE op — two walks per collective
        (ancestors via deps, descendants via users) keep the whole
        analysis O(#collectives x E) instead of materializing a
        closure per op (a non-tiny step module has 10^4+ ops and this
        runs inside every step_cost_report)."""
        out: set = set()
        stack = list(edges.get(name, ()))
        while stack:
            d = stack.pop()
            if d in out:
                continue
            out.add(d)
            stack.extend(edges.get(d, ()))
        return out

    compute: Dict[str, int] = {}       # name -> result bytes
    for name, rhs in ops:
        m = _COMPUTE_RE.match(rhs)
        if m:
            compute[name] = _shape_bytes(m.group(1))

    def real_consumers(name: str) -> set:
        """Schedule-independent consumers: dataflow resolved through
        pass-through ops. The computation root maps to the ``_ROOT``
        sentinel — a result that only reaches the root tuple is
        *carried* (consumed by the next loop iteration)."""
        out: set = set()
        stack = list(users.get(name, ()))
        seen: set = set()
        while stack:
            u = stack.pop()
            if u in seen:
                continue
            seen.add(u)
            if kind_of.get(u) in _PASSTHROUGH_KINDS:
                # a pass-through ROOT (the while body's carry tuple)
                # is the "next iteration" sentinel; mid-graph
                # pass-throughs are resolved through
                if u == root:
                    out.add(_ROOT)
                else:
                    stack.extend(users.get(u, ()))
            else:
                out.add(u)
        return out

    # collect collectives: sync ops, and start/done pairs (done's first
    # operand chain leads back to the start op)
    total = 0
    exposed = 0
    lines: List[str] = []
    done_for: Dict[str, Tuple[str, str]] = {}
    rhs_of = dict(ops)
    for name, rhs in ops:
        m = re.search(r"\b(" + "|".join(COLLECTIVE_KINDS) + r")-done\(",
                      rhs)
        if m:
            starts = [d for d in deps[name] if f"{m.group(1)}-start(" in
                      rhs_of.get(d, "")]
            if starts:
                done_for[starts[0]] = (name, rhs)
    for name, rhs in ops:
        m = _COLL_RE.search("= " + rhs if not rhs.startswith("=") else rhs)
        if m is None:
            continue
        kind = m.group(2)
        is_start = f"{kind}-start(" in rhs
        if is_start and name in done_for:
            dname, drhs = done_for[name]
            paren = drhs.find(f"{kind}-done(")
            nbytes = _shape_bytes(drhs[:paren])
            desc = reach(name, users)
            window = [w for w, _ in ops[index[name] + 1:index[dname]]
                      if w in compute and w not in desc]
            hidden = sum(compute[w] for w in window)
            total += nbytes
            # bytes-weighted: the window must hold at least the
            # collective's own bytes of independent compute
            if hidden >= nbytes and hidden > 0:
                lines.append(
                    f"{kind} {nbytes}B hidden behind {len(window)} "
                    f"compute op(s) (~{hidden}B results) in its "
                    "start/done window")
                continue
            exposed += nbytes
            if hidden > 0:
                lines.append(
                    f"{kind} {nbytes}B EXPOSED (async window holds only "
                    f"~{hidden}B of independent compute across "
                    f"{len(window)} op(s) — a thin window cannot hide "
                    f"{nbytes}B)")
            else:
                lines.append(f"{kind} {nbytes}B EXPOSED (async pair "
                             "with an empty window)")
            continue
        nbytes = _shape_bytes(m.group(1))
        total += nbytes
        desc = reach(name, users)
        anc = reach(name, deps)
        consumers = real_consumers(name)
        if consumers <= {_ROOT} and not is_entry:
            # carried: the result flows only to a NON-ENTRY root tuple
            # (a while-body carry) — the next iteration consumes it, so
            # every independent op of this body can hide it (the
            # double-buffered prefetch shape). In ENTRY the root IS the
            # program output: a collective feeding only it stalls the
            # step before returning and stays EXPOSED below.
            indep_bytes = sum(b for c, b in compute.items()
                              if c != name and c not in desc
                              and c not in anc)
            if indep_bytes >= nbytes and indep_bytes > 0:
                lines.append(
                    f"{kind} {nbytes}B hidden (double-buffered: result "
                    "carried to the next loop iteration; "
                    f"~{indep_bytes}B independent compute in the body "
                    "hides it)")
                continue
        else:
            non_root = [index[c] for c in consumers if c != _ROOT]
            # no real consumer at all (ENTRY-carried: the result feeds
            # only the program output) — nothing downstream ever waits
            # overlapped on it; the step stalls before returning, so it
            # falls through to EXPOSED rather than crediting the whole
            # trailing schedule as a hiding window
            if non_root:
                first = min(non_root)
                window = [w for w, _ in ops[index[name] + 1:first]
                          if w in compute and w not in desc]
                gap_bytes = sum(compute[w] for w in window)
                if gap_bytes >= nbytes and gap_bytes > 0:
                    lines.append(
                        f"{kind} {nbytes}B hidden (scheduled "
                        f"{first - index[name]} op(s) ahead of its "
                        f"first consumer; ~{gap_bytes}B independent "
                        "compute in the gap hides it)")
                    continue
        exposed += nbytes
        related = anc | desc
        indep = [c for c in compute if c != name and c not in related]
        indep_bytes = sum(compute[c] for c in indep)
        lines.append(
            f"{kind} {nbytes}B EXPOSED (synchronous); independent "
            f"compute available to hide it: {len(indep)} op(s) "
            f"~{indep_bytes}B results")
    return exposed, total, lines


@dataclasses.dataclass
class StepCostReport:
    """Structured per-step cost/memory ledger of one compiled program."""
    flops: float = 0.0               # per-device-program FLOPs per step
    bytes_accessed: float = 0.0      # HBM traffic per step (per device)
    transcendentals: float = 0.0
    temp_bytes: int = 0              # peak scratch (activations live here)
    argument_bytes: int = 0
    output_bytes: int = 0
    alias_bytes: int = 0             # donated inputs aliased into outputs
    generated_code_bytes: int = 0
    collective_counts: Dict[str, int] = dataclasses.field(
        default_factory=dict)
    collective_bytes: int = 0
    collective_lines: List[str] = dataclasses.field(default_factory=list)
    # network attribution (collective_axis_stats): every collective's
    # bytes split by the fabric its replica groups span — intra-slice
    # ICI vs the inter-slice DCN link. On a single-slice mesh
    # dcn_bytes == 0 by construction; on a hybrid mesh dcn_bytes is THE
    # budgeted number DCN_SYNC=hier shrinks by 1/ici_size.
    ici_bytes: int = 0
    dcn_bytes: int = 0
    dcn_lines: List[str] = dataclasses.field(default_factory=list)
    # overlap/exposure ledger (overlap_stats): collective bytes the
    # schedule leaves EXPOSED (no compute hides their latency), the
    # hidden fraction, and the per-collective attribution lines — the
    # budget fields ROADMAP #3's overlap work moves
    exposed_collective_bytes: int = 0
    overlap_frac: float = 1.0
    exposure_lines: List[str] = dataclasses.field(default_factory=list)
    n_devices: int = 1
    tokens_per_step: Optional[int] = None

    # -- derived ------------------------------------------------------
    def flops_per_token(self) -> Optional[float]:
        if not self.tokens_per_step:
            return None
        # report flops are per device; tokens_per_step is global
        return self.flops * self.n_devices / self.tokens_per_step

    def ceilings(self, chip: Optional[ChipSpec] = None) -> Dict[str, float]:
        """Roofline at ``chip`` (default: the attached device kind):
        step-time lower bounds from compute, HBM traffic, and the
        network (EXPOSED collective bytes over the fabric they span —
        ICI intra-slice, DCN across; hidden bytes overlap compute by
        definition and never bound the step), and the MFU ceiling the
        binding term implies. An asserted *analytic* bound — measured
        MFU can only be below it."""
        chip = chip or chip_spec_for_devices()
        t_compute = self.flops / chip.peak_flops
        t_hbm = self.bytes_accessed / chip.hbm_bytes_per_s
        # exposed bytes split by fabric in the same dcn:ici proportion
        # as the total traffic (the schedule does not tag exposure per
        # fabric); with no attribution recorded everything rides ICI
        total_coll = max(self.collective_bytes, 1)
        exp_dcn = self.exposed_collective_bytes * self.dcn_bytes \
            / total_coll
        exp_ici = self.exposed_collective_bytes - exp_dcn
        t_ici = exp_ici / chip.ici_bytes_per_s
        t_dcn = exp_dcn / chip.dcn_bytes_per_s
        bound = max(t_compute, t_hbm, t_ici + t_dcn, 1e-30)
        return {
            "chip": chip.name,
            "compute_bound_step_s": t_compute,
            "hbm_bound_step_s": t_hbm,
            "ici_bound_step_s": t_ici,
            "dcn_bound_step_s": t_dcn,
            "mfu_ceiling": t_compute / bound,
        }

    def to_dict(self, *, include_lines: bool = True) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        if not include_lines:
            d.pop("collective_lines")
            d.pop("exposure_lines")
            d.pop("dcn_lines")
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "StepCostReport":
        known = {f.name for f in dataclasses.fields(StepCostReport)}
        return StepCostReport(**{k: v for k, v in d.items() if k in known})

    def summary(self) -> Dict[str, Any]:
        """Compact form for one-line JSON records (bench output)."""
        out = {
            "flops_per_step": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "temp_bytes": self.temp_bytes,
            "argument_bytes": self.argument_bytes,
            "alias_bytes": self.alias_bytes,
            "collectives": {k: v for k, v in self.collective_counts.items()
                            if v},
            "collective_bytes": self.collective_bytes,
            "ici_bytes": self.ici_bytes,
            "dcn_bytes": self.dcn_bytes,
            "exposed_collective_bytes": self.exposed_collective_bytes,
            "overlap_frac": self.overlap_frac,
        }
        fpt = self.flops_per_token()
        if fpt is not None:
            out["flops_per_token"] = round(fpt, 1)
        out.update({k: v for k, v in self.ceilings().items()
                    if k in ("chip", "mfu_ceiling")})
        return out


def step_cost_report(compiled, *, tokens_per_step: Optional[int] = None,
                     num_slices: Optional[int] = None) -> StepCostReport:
    """Build a :class:`StepCostReport` from ``jit(...).lower(...)
    .compile()`` output. Works with no accelerator attached — every
    number comes from XLA's compile-time analyses.

    ``num_slices``: the DCN topology the program's collectives are
    attributed against (``ici_bytes``/``dcn_bytes``; default: the
    ``slice_assignments`` contract — real devices' ``.slice_index``,
    else ``$NUM_SLICES``, else one slice = everything ICI)."""
    report = StepCostReport(n_devices=max(len(jax.devices()), 1),
                            tokens_per_step=tokens_per_step)
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jaxlib: one dict per... module
        ca = ca[0] if ca else {}
    if ca:
        report.flops = float(ca.get("flops", 0.0))
        report.bytes_accessed = float(ca.get("bytes accessed", 0.0))
        report.transcendentals = float(ca.get("transcendentals", 0.0))
    ma = compiled.memory_analysis()
    if ma is not None:
        report.temp_bytes = int(getattr(ma, "temp_size_in_bytes", 0))
        report.argument_bytes = int(getattr(ma, "argument_size_in_bytes", 0))
        report.output_bytes = int(getattr(ma, "output_size_in_bytes", 0))
        report.alias_bytes = int(getattr(ma, "alias_size_in_bytes", 0))
        report.generated_code_bytes = int(
            getattr(ma, "generated_code_size_in_bytes", 0))
    try:
        hlo = compiled.as_text()
    except Exception:  # noqa: BLE001 - some backends cannot re-text
        hlo = ""
    # one parse of the (potentially multi-MB) HLO text, shared by the
    # three collective analyses
    comps = _computation_lines(hlo)
    trips = _while_trip_counts(hlo, comps)
    counts, cbytes, lines = collective_stats(hlo, _comps=comps,
                                             _trips=trips)
    report.collective_counts = counts
    report.collective_bytes = cbytes
    report.collective_lines = lines
    from gke_ray_train_tpu.parallel.mesh import slice_assignments
    slice_map = slice_assignments(jax.devices(), num_slices)
    ici, dcn, dcn_lines = collective_axis_stats(hlo, slice_map,
                                                _comps=comps,
                                                _trips=trips)
    report.ici_bytes = ici
    report.dcn_bytes = dcn
    report.dcn_lines = dcn_lines
    exposed, frac, exp_lines = overlap_stats(hlo, _trips=trips)
    report.exposed_collective_bytes = exposed
    report.overlap_frac = frac
    report.exposure_lines = exp_lines
    return report


def assert_state_donation(compiled, state: Any,
                          *, min_frac: float = 0.8) -> int:
    """Assert the train-state donation actually held: the aliased bytes
    XLA reports must cover ≥ ``min_frac`` of the state's own bytes
    (params + optimizer state alias into their updated outputs — the
    memory-headroom contract ``donate_argnums=(0, ...)`` exists for).
    Returns the aliased byte count. Donated *batch* buffers have no
    matching output, so they are invisible to ``memory_analysis`` —
    their freeing is asserted structurally (``donate_argnums``), not
    here."""
    ma = compiled.memory_analysis()
    if ma is None:  # pragma: no cover - backend without the analysis
        return -1
    state_bytes = sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(state)
        if hasattr(x, "dtype")) // max(len(jax.devices()), 1)
    alias = int(ma.alias_size_in_bytes)
    if alias < min_frac * state_bytes:
        raise AssertionError(
            f"state donation did not hold: {alias} aliased bytes vs "
            f"~{state_bytes} per-device state bytes (donated buffers "
            "not reused — check donate_argnums and output layout)")
    return alias
