"""Compile-level step cost accounting — numbers that need no accelerator.

``StepCostReport`` extracts XLA's own ledger from an AOT-compiled
executable: ``cost_analysis()`` (flops, bytes accessed),
``memory_analysis()`` (peak temp / argument / output / aliased bytes)
and the optimized HLO text (collective count & bytes by kind). All of
it comes from lowering + compilation alone, so the identical report is
produced on the 8-fake-device CPU mesh CI runs on and on a v5e-16 —
which is what makes the budget harness (:mod:`perf.budget`) a tier-1
regression gate rather than a hardware benchmark.

Numbers describe the **per-device SPMD program** XLA compiled (under
GSPMD the compiled module is the per-device partition; flops/bytes are
that partition's). The analytic MFU ceiling is the classic roofline:
``t_compute = flops / peak_flops``, ``t_hbm = bytes / hbm_bw``, ceiling
= ``t_compute / max(t_compute, t_hbm)`` at a given chip spec.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Tuple

import jax


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    name: str
    peak_flops: float        # dense bf16 FLOP/s per chip
    hbm_bytes_per_s: float   # HBM bandwidth per chip
    hbm_bytes: float         # HBM capacity per chip
    vmem_bytes: float = 16 * 2**20   # on-chip vector memory per core


CHIP_SPECS = {
    "v5e": ChipSpec("v5e", 197e12, 819e9, 16 * 2**30),
    "v5p": ChipSpec("v5p", 459e12, 2765e9, 95 * 2**30),
    "v4": ChipSpec("v4", 275e12, 1228e9, 32 * 2**30),
    "v6e": ChipSpec("v6e", 918e12, 1640e9, 32 * 2**30),
    # nominal CPU spec: keeps ceilings finite for the CI mesh; vmem uses
    # the TPU figure so kernelcheck KER002 verdicts match real chips
    "cpu": ChipSpec("cpu", 1e12, 50e9, 8 * 2**30),
}

# device_kind substring → spec key (same matching discipline as
# train.metrics.PEAK_FLOPS; longest key wins)
_KIND_TO_SPEC = {
    "v5 lite": "v5e", "v5e": "v5e", "v5p": "v5p", "v5": "v5p",
    "v4": "v4", "v6 lite": "v6e", "v6e": "v6e", "cpu": "cpu",
}


def chip_spec_for_devices(default: str = "v5e") -> ChipSpec:
    kind = jax.devices()[0].device_kind.lower()
    for k, spec in sorted(_KIND_TO_SPEC.items(), key=lambda kv: -len(kv[0])):
        if k in kind:
            return CHIP_SPECS[spec]
    return CHIP_SPECS[default]


COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "all-to-all", "collective-permute")

_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
                "f16": 2, "bf16": 2, "s32": 4, "u32": 4, "f32": 4,
                "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16}
_SHAPE_RE = re.compile(r"\b(pred|bf16|f16|f32|f64|s8|u8|s16|u16|s32|u32"
                       r"|s64|u64|c64|c128)\[([0-9,]*)\]")
# "<result-type> <kind>(" — also matches async "-start" forms; "-done"
# deliberately does not match (it would double-count the async pair)
_COLL_RE = re.compile(
    r"=\s*(.*?)\s(" + "|".join(COLLECTIVE_KINDS) + r")(?:-start)?\(")


def _shape_bytes(type_str: str) -> int:
    total = 0
    for dtype, dims in _SHAPE_RE.findall(type_str):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def collective_stats(hlo_text: str) -> Tuple[Dict[str, int], int, List[str]]:
    """(count-by-kind, total result bytes, matched HLO lines) for every
    collective in an optimized HLO module. The lines ride along so a
    budget miss can print the actual offending ops, not just a count."""
    counts = {k: 0 for k in COLLECTIVE_KINDS}
    total_bytes = 0
    lines: List[str] = []
    for line in hlo_text.splitlines():
        m = _COLL_RE.search(line)
        if m is None:
            continue
        counts[m.group(2)] += 1
        total_bytes += _shape_bytes(m.group(1))
        lines.append(line.strip()[:200])
    return counts, total_bytes, lines


# ---------------------------------------------------------------------------
# overlap / exposure analysis of the scheduled entry computation
# ---------------------------------------------------------------------------

_ENTRY_OP_RE = re.compile(r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*(.+)$")
_COMPUTE_KINDS = ("dot", "convolution", "fusion", "custom-call")
_COMPUTE_RE = re.compile(
    r"^(.*?)\s(" + "|".join(_COMPUTE_KINDS) + r")\(")


def _computations(hlo_text: str
                  ) -> List[Tuple[List[Tuple[str, str]], bool]]:
    """Per-computation ([(name, rhs)], is_entry) pairs, in schedule
    order (the optimized module prints each computation's ops in the
    order the scheduler chose). Collectives live in the ENTRY
    computation AND in loop bodies (a scanned grad-accum step keeps its
    collectives inside the while body), so exposure is analyzed per
    computation — and the carried-to-root classification needs to know
    which root is a LOOP carry vs the program output."""
    comps: List[Tuple[List[Tuple[str, str]], bool]] = []
    cur: Optional[List[Tuple[str, str]]] = None
    is_entry = False
    for line in hlo_text.splitlines():
        stripped = line.strip()
        if cur is None:
            # "%comp (args) -> type {" or "ENTRY %main (...) -> ... {"
            if stripped.endswith("{") and ("->" in stripped
                                           or stripped.startswith("ENTRY")):
                cur = []
                is_entry = stripped.startswith("ENTRY")
            continue
        if stripped == "}" or line.startswith("}"):
            comps.append((cur, is_entry))
            cur = None
            continue
        m = _ENTRY_OP_RE.match(line)
        if m:
            cur.append((m.group(1), m.group(2)))
    if cur:
        comps.append((cur, is_entry))
    return comps


def overlap_stats(hlo_text: str) -> Tuple[int, float, List[str]]:
    """(exposed_collective_bytes, overlap_frac, attribution lines).

    Walks the scheduled computations and classifies every collective as
    *hidden* or *EXPOSED*. Three ways to be hidden, all bytes-weighted
    (a window must hold at least the collective's own result bytes of
    independent compute — a 1-op window cannot mask a multi-MB
    all-gather):

    - an async ``-start``/``-done`` pair with enough independent
      compute scheduled inside the window;
    - a synchronous collective *scheduled ahead of its first consumer*
      with enough independent compute in the gap (the latency-hiding
      schedule already moved it — dataflow through copies / bitcasts /
      tuples / opt-barriers is resolved, so a fence does not count as
      a consumer);
    - a synchronous collective whose result is consumed only by the
      NEXT loop iteration (it flows to the while body's root tuple —
      the double-buffered prefetch shape ``train/overlap.py`` emits:
      layer *k+1*'s all-gather is issued while layer *k* computes, so
      the whole body's independent compute is available to hide it.
      The CPU list scheduler shows no async pair, but the *dataflow*
      is schedule-independent — an async runtime (TPU DMA engines,
      XLA's latency-hiding scheduler) overlaps a carried collective by
      construction, which is what lets CPU-mesh budgets assert the
      overlap claim while the accelerator backend is dark).

    Everything else is EXPOSED (the step stalls for the full fabric
    latency); the attribution line reports ``hidden_compute_bytes`` —
    the independent compute (neither ancestor nor descendant) a
    latency-hiding schedule COULD move into its window. That number is
    the actionable half: ``exposed > 0`` with independent compute
    available is exactly the overlap opportunity ROADMAP #3 asserts
    through budgets.

    ``overlap_frac`` = hidden bytes / total collective bytes (1.0 when
    the program has no collectives — nothing is exposed)."""
    exposed = 0
    total = 0
    lines: List[str] = []
    for ops, is_entry in _computations(hlo_text):
        e, t, ls = _overlap_in_computation(ops, is_entry=is_entry)
        exposed += e
        total += t
        lines.extend(ls)
    frac = 1.0 if total == 0 else round(1.0 - exposed / total, 6)
    return exposed, frac, lines


# ops that move/regroup data without computing: dataflow is resolved
# THROUGH them when finding a collective's real consumers (a copy or a
# scheduling fence between a prefetched all-gather and the loop root
# must not read as "consumed immediately")
_PASSTHROUGH_KINDS = frozenset({
    "copy", "bitcast", "tuple", "get-tuple-element", "opt-barrier",
    "optimization-barrier"})
_ROOT = "#root"   # sentinel consumer: the computation's root tuple


def _overlap_in_computation(ops: List[Tuple[str, str]], *,
                            is_entry: bool = False
                            ) -> Tuple[int, int, List[str]]:
    index = {name: i for i, (name, _) in enumerate(ops)}
    deps: Dict[str, List[str]] = {}
    users: Dict[str, List[str]] = {n: [] for n, _ in ops}
    kind_of: Dict[str, str] = {}
    for name, rhs in ops:
        # the opcode is the first WHITESPACE-PRECEDED word directly
        # followed by "(" — result types never contain one, a
        # tuple-typed result's leading "(f32[...], ...)" holds no such
        # pair, and TPU tile-layout annotations ("{1,0:T(8,128)}")
        # prepend ":" not whitespace, so they can't shadow the opcode
        km = re.search(r"(?<=\s)([\w\-]+)\(", rhs)
        kind_of[name] = km.group(1) if km else ""
        paren = rhs.find(" " + kind_of[name] + "(") if km else -1
        body = rhs[paren:] if paren >= 0 else rhs
        deps[name] = [d for d in re.findall(r"%([\w.\-]+)", body)
                      if d in index and d != name]
        for d in deps[name]:
            users[d].append(name)
    root = ops[-1][0] if ops else None

    def reach(name: str, edges: Dict[str, List[str]]) -> set:
        """Transitive closure from ONE op — two walks per collective
        (ancestors via deps, descendants via users) keep the whole
        analysis O(#collectives x E) instead of materializing a
        closure per op (a non-tiny step module has 10^4+ ops and this
        runs inside every step_cost_report)."""
        out: set = set()
        stack = list(edges.get(name, ()))
        while stack:
            d = stack.pop()
            if d in out:
                continue
            out.add(d)
            stack.extend(edges.get(d, ()))
        return out

    compute: Dict[str, int] = {}       # name -> result bytes
    for name, rhs in ops:
        m = _COMPUTE_RE.match(rhs)
        if m:
            compute[name] = _shape_bytes(m.group(1))

    def real_consumers(name: str) -> set:
        """Schedule-independent consumers: dataflow resolved through
        pass-through ops. The computation root maps to the ``_ROOT``
        sentinel — a result that only reaches the root tuple is
        *carried* (consumed by the next loop iteration)."""
        out: set = set()
        stack = list(users.get(name, ()))
        seen: set = set()
        while stack:
            u = stack.pop()
            if u in seen:
                continue
            seen.add(u)
            if kind_of.get(u) in _PASSTHROUGH_KINDS:
                # a pass-through ROOT (the while body's carry tuple)
                # is the "next iteration" sentinel; mid-graph
                # pass-throughs are resolved through
                if u == root:
                    out.add(_ROOT)
                else:
                    stack.extend(users.get(u, ()))
            else:
                out.add(u)
        return out

    # collect collectives: sync ops, and start/done pairs (done's first
    # operand chain leads back to the start op)
    total = 0
    exposed = 0
    lines: List[str] = []
    done_for: Dict[str, Tuple[str, str]] = {}
    rhs_of = dict(ops)
    for name, rhs in ops:
        m = re.search(r"\b(" + "|".join(COLLECTIVE_KINDS) + r")-done\(",
                      rhs)
        if m:
            starts = [d for d in deps[name] if f"{m.group(1)}-start(" in
                      rhs_of.get(d, "")]
            if starts:
                done_for[starts[0]] = (name, rhs)
    for name, rhs in ops:
        m = _COLL_RE.search("= " + rhs if not rhs.startswith("=") else rhs)
        if m is None:
            continue
        kind = m.group(2)
        is_start = f"{kind}-start(" in rhs
        if is_start and name in done_for:
            dname, drhs = done_for[name]
            paren = drhs.find(f"{kind}-done(")
            nbytes = _shape_bytes(drhs[:paren])
            desc = reach(name, users)
            window = [w for w, _ in ops[index[name] + 1:index[dname]]
                      if w in compute and w not in desc]
            hidden = sum(compute[w] for w in window)
            total += nbytes
            # bytes-weighted: the window must hold at least the
            # collective's own bytes of independent compute
            if hidden >= nbytes and hidden > 0:
                lines.append(
                    f"{kind} {nbytes}B hidden behind {len(window)} "
                    f"compute op(s) (~{hidden}B results) in its "
                    "start/done window")
                continue
            exposed += nbytes
            if hidden > 0:
                lines.append(
                    f"{kind} {nbytes}B EXPOSED (async window holds only "
                    f"~{hidden}B of independent compute across "
                    f"{len(window)} op(s) — a thin window cannot hide "
                    f"{nbytes}B)")
            else:
                lines.append(f"{kind} {nbytes}B EXPOSED (async pair "
                             "with an empty window)")
            continue
        nbytes = _shape_bytes(m.group(1))
        total += nbytes
        desc = reach(name, users)
        anc = reach(name, deps)
        consumers = real_consumers(name)
        if consumers <= {_ROOT} and not is_entry:
            # carried: the result flows only to a NON-ENTRY root tuple
            # (a while-body carry) — the next iteration consumes it, so
            # every independent op of this body can hide it (the
            # double-buffered prefetch shape). In ENTRY the root IS the
            # program output: a collective feeding only it stalls the
            # step before returning and stays EXPOSED below.
            indep_bytes = sum(b for c, b in compute.items()
                              if c != name and c not in desc
                              and c not in anc)
            if indep_bytes >= nbytes and indep_bytes > 0:
                lines.append(
                    f"{kind} {nbytes}B hidden (double-buffered: result "
                    "carried to the next loop iteration; "
                    f"~{indep_bytes}B independent compute in the body "
                    "hides it)")
                continue
        else:
            non_root = [index[c] for c in consumers if c != _ROOT]
            # no real consumer at all (ENTRY-carried: the result feeds
            # only the program output) — nothing downstream ever waits
            # overlapped on it; the step stalls before returning, so it
            # falls through to EXPOSED rather than crediting the whole
            # trailing schedule as a hiding window
            if non_root:
                first = min(non_root)
                window = [w for w, _ in ops[index[name] + 1:first]
                          if w in compute and w not in desc]
                gap_bytes = sum(compute[w] for w in window)
                if gap_bytes >= nbytes and gap_bytes > 0:
                    lines.append(
                        f"{kind} {nbytes}B hidden (scheduled "
                        f"{first - index[name]} op(s) ahead of its "
                        f"first consumer; ~{gap_bytes}B independent "
                        "compute in the gap hides it)")
                    continue
        exposed += nbytes
        related = anc | desc
        indep = [c for c in compute if c != name and c not in related]
        indep_bytes = sum(compute[c] for c in indep)
        lines.append(
            f"{kind} {nbytes}B EXPOSED (synchronous); independent "
            f"compute available to hide it: {len(indep)} op(s) "
            f"~{indep_bytes}B results")
    return exposed, total, lines


@dataclasses.dataclass
class StepCostReport:
    """Structured per-step cost/memory ledger of one compiled program."""
    flops: float = 0.0               # per-device-program FLOPs per step
    bytes_accessed: float = 0.0      # HBM traffic per step (per device)
    transcendentals: float = 0.0
    temp_bytes: int = 0              # peak scratch (activations live here)
    argument_bytes: int = 0
    output_bytes: int = 0
    alias_bytes: int = 0             # donated inputs aliased into outputs
    generated_code_bytes: int = 0
    collective_counts: Dict[str, int] = dataclasses.field(
        default_factory=dict)
    collective_bytes: int = 0
    collective_lines: List[str] = dataclasses.field(default_factory=list)
    # overlap/exposure ledger (overlap_stats): collective bytes the
    # schedule leaves EXPOSED (no compute hides their latency), the
    # hidden fraction, and the per-collective attribution lines — the
    # budget fields ROADMAP #3's overlap work moves
    exposed_collective_bytes: int = 0
    overlap_frac: float = 1.0
    exposure_lines: List[str] = dataclasses.field(default_factory=list)
    n_devices: int = 1
    tokens_per_step: Optional[int] = None

    # -- derived ------------------------------------------------------
    def flops_per_token(self) -> Optional[float]:
        if not self.tokens_per_step:
            return None
        # report flops are per device; tokens_per_step is global
        return self.flops * self.n_devices / self.tokens_per_step

    def ceilings(self, chip: Optional[ChipSpec] = None) -> Dict[str, float]:
        """Roofline at ``chip`` (default: the attached device kind):
        step-time lower bounds from compute and HBM traffic, and the
        MFU ceiling their ratio implies. An asserted *analytic* bound —
        measured MFU can only be below it."""
        chip = chip or chip_spec_for_devices()
        t_compute = self.flops / chip.peak_flops
        t_hbm = self.bytes_accessed / chip.hbm_bytes_per_s
        bound = max(t_compute, t_hbm, 1e-30)
        return {
            "chip": chip.name,
            "compute_bound_step_s": t_compute,
            "hbm_bound_step_s": t_hbm,
            "mfu_ceiling": t_compute / bound,
        }

    def to_dict(self, *, include_lines: bool = True) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        if not include_lines:
            d.pop("collective_lines")
            d.pop("exposure_lines")
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "StepCostReport":
        known = {f.name for f in dataclasses.fields(StepCostReport)}
        return StepCostReport(**{k: v for k, v in d.items() if k in known})

    def summary(self) -> Dict[str, Any]:
        """Compact form for one-line JSON records (bench output)."""
        out = {
            "flops_per_step": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "temp_bytes": self.temp_bytes,
            "argument_bytes": self.argument_bytes,
            "alias_bytes": self.alias_bytes,
            "collectives": {k: v for k, v in self.collective_counts.items()
                            if v},
            "collective_bytes": self.collective_bytes,
            "exposed_collective_bytes": self.exposed_collective_bytes,
            "overlap_frac": self.overlap_frac,
        }
        fpt = self.flops_per_token()
        if fpt is not None:
            out["flops_per_token"] = round(fpt, 1)
        out.update({k: v for k, v in self.ceilings().items()
                    if k in ("chip", "mfu_ceiling")})
        return out


def step_cost_report(compiled, *, tokens_per_step: Optional[int] = None
                     ) -> StepCostReport:
    """Build a :class:`StepCostReport` from ``jit(...).lower(...)
    .compile()`` output. Works with no accelerator attached — every
    number comes from XLA's compile-time analyses."""
    report = StepCostReport(n_devices=max(len(jax.devices()), 1),
                            tokens_per_step=tokens_per_step)
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jaxlib: one dict per... module
        ca = ca[0] if ca else {}
    if ca:
        report.flops = float(ca.get("flops", 0.0))
        report.bytes_accessed = float(ca.get("bytes accessed", 0.0))
        report.transcendentals = float(ca.get("transcendentals", 0.0))
    ma = compiled.memory_analysis()
    if ma is not None:
        report.temp_bytes = int(getattr(ma, "temp_size_in_bytes", 0))
        report.argument_bytes = int(getattr(ma, "argument_size_in_bytes", 0))
        report.output_bytes = int(getattr(ma, "output_size_in_bytes", 0))
        report.alias_bytes = int(getattr(ma, "alias_size_in_bytes", 0))
        report.generated_code_bytes = int(
            getattr(ma, "generated_code_size_in_bytes", 0))
    try:
        hlo = compiled.as_text()
    except Exception:  # noqa: BLE001 - some backends cannot re-text
        hlo = ""
    counts, cbytes, lines = collective_stats(hlo)
    report.collective_counts = counts
    report.collective_bytes = cbytes
    report.collective_lines = lines
    exposed, frac, exp_lines = overlap_stats(hlo)
    report.exposed_collective_bytes = exposed
    report.overlap_frac = frac
    report.exposure_lines = exp_lines
    return report


def assert_state_donation(compiled, state: Any,
                          *, min_frac: float = 0.8) -> int:
    """Assert the train-state donation actually held: the aliased bytes
    XLA reports must cover ≥ ``min_frac`` of the state's own bytes
    (params + optimizer state alias into their updated outputs — the
    memory-headroom contract ``donate_argnums=(0, ...)`` exists for).
    Returns the aliased byte count. Donated *batch* buffers have no
    matching output, so they are invisible to ``memory_analysis`` —
    their freeing is asserted structurally (``donate_argnums``), not
    here."""
    ma = compiled.memory_analysis()
    if ma is None:  # pragma: no cover - backend without the analysis
        return -1
    state_bytes = sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(state)
        if hasattr(x, "dtype")) // max(len(jax.devices()), 1)
    alias = int(ma.alias_size_in_bytes)
    if alias < min_frac * state_bytes:
        raise AssertionError(
            f"state donation did not hold: {alias} aliased bytes vs "
            f"~{state_bytes} per-device state bytes (donated buffers "
            "not reused — check donate_argnums and output layout)")
    return alias
