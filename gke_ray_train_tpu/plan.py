"""ExecutionPlan — one declarative, validated plan object (ROADMAP #5).

The knobs that shape a run's *execution* (as opposed to its data or
optimization hyperparameters) historically lived in four dialects:

1. flat UPPER_CASE JSON config keys (``config.py`` KNOWN_KEYS),
2. env vars forwarded to Ray workers by the trainer,
3. ``run_training(...)`` / ``make_train_step(...)`` kwargs,
4. per-preset budget JSONs (``tests/budgets/*.json``).

:class:`ExecutionPlan` collapses them: one frozen dataclass holding the
mesh axes + sizes, the logical PartitionSpecs for params/optimizer/batch
(delegated to the canonical tables in ``models/transformer.py`` /
``train/step.py`` so specs can never fork), the donation policy, the
AOT/compile-cache policy, the runtime guards, and the budget preset —
with a constructor per legacy dialect (:meth:`from_config`,
:meth:`from_env`, :meth:`from_kwargs`) that produces an IDENTICAL plan
(and fingerprint) for identical settings.

``fingerprint()`` is the plan's stable identity: a digest of the
canonical field dict, independent of process, host, and backend. It is
recorded in budget JSONs (``_plan_fingerprint``), BENCH records, and
AOT sidecar keys (``perf/cache.py`` composes it with the runtime
topology fingerprint, which it thereby subsumes: two runs share a
compiled artifact only when both the physical topology AND the declared
plan agree).

Everything here is statically checkable with no accelerator —
``analysis/plancheck.py`` verifies feasibility/portability/consistency
on the same CPU-only CI runner that runs shardlint.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

from gke_ray_train_tpu.parallel.mesh import MESH_AXES, BATCH_AXES, MeshConfig


class PlanError(ValueError):
    """An ExecutionPlan field failed validation."""


# chip counts of the topology presets plancheck verifies against. The
# real accelerator backend being dark (ROADMAP preamble), these are
# *declared* shapes — the point is that every one of them is checkable
# via shape/divisibility arithmetic with zero hardware. cpu-N are the
# fake-device CI meshes (save-on-8 → restore-on-4/16 is the static half
# of elastic resume, ROADMAP #1).
CHIP_COUNTS: Dict[str, int] = {
    "cpu-4": 4, "cpu-8": 8, "cpu-16": 16,
    "v5e-4": 4, "v5e-8": 8, "v5e-16": 16, "v5e-32": 32, "v5e-64": 64,
    "v5p-8": 8, "v5p-16": 16, "v5p-32": 32, "v5p-64": 64, "v5p-128": 128,
}

# non-preset chip counts are still declarable as "<family>-<n>" — an
# elastic replan onto a 12-chip survivor pool must be able to NAME its
# topology even though no nodepool preset ships that shape
TOPOLOGY_FAMILIES: Tuple[str, ...] = tuple(sorted(
    {k.split("-", 1)[0] for k in CHIP_COUNTS}))

_TRANSFER_GUARD_MODES = (None, "log", "disallow")

# communication/compute overlap modes for the train step (ROADMAP #3)
OVERLAP_MODES = ("off", "xla", "manual")

# cross-slice gradient-sync modes on a hybrid multi-slice mesh
# (ROADMAP #4; parallel/hierarchical.py) and the optional DCN-hop
# compression arm
DCN_SYNC_MODES = ("flat", "hier")
DCN_COMPRESS_MODES = ("none", "bf16")

# speculative-decoding draft sources for the serving engine
# (serve/engine.py): "self" drafts with the target model itself (the
# accept-all arm), "distilled" expects a separate small draft model
SPEC_DRAFT_MODES = ("none", "self", "distilled")

# the compiler flags overlap="xla" applies on a TPU compile surface:
# XLA's latency-hiding scheduler converts the FSDP all-gathers /
# grad reduces into async start/done pairs and schedules independent
# compute into their windows — the budget fields overlap_stats pins.
# TPU-only: other backends reject the flag names outright, so
# overlap_compiler_options() gates on the attached backend and the
# compile falls back to plain flags when a backend refuses them.
# python bools, NOT "true" strings: jaxlib's option parser accepts
# bool values / "True" but rejects lowercase "true" with
# INVALID_ARGUMENT at compile time
XLA_OVERLAP_OPTIONS: Dict[str, bool] = {
    "xla_tpu_enable_latency_hiding_scheduler": True,
    "xla_enable_async_all_gather": True,
    "xla_enable_async_collective_permute": True,
    "xla_tpu_enable_async_collective_fusion": True,
    "xla_tpu_enable_async_collective_fusion_fuse_all_gather": True,
}


def overlap_compiler_options(plan: "ExecutionPlan"
                             ) -> Optional[Dict[str, bool]]:
    """The compiler-option dict ``overlap="xla"`` adds to the plan's
    compile surface, or None when the mode is off/manual or the
    attached backend is not a TPU (the flags are TPU-scheduler knobs;
    XLA:CPU rejects unknown option names, and the CPU-mesh program is
    the bitwise baseline either way)."""
    if plan.overlap != "xla":
        return None
    import jax
    try:
        backend = jax.default_backend()
    except Exception:  # noqa: BLE001 - dead backend: plain compile
        return None
    if backend != "tpu":
        return None
    return dict(XLA_OVERLAP_OPTIONS)


def _serve_quant_kinds() -> Tuple[str, ...]:
    """ops/quant.py owns the serving quantization vocabulary; imported
    lazily (validation time only) so plan.py stays importable without
    pulling the jax-heavy ops package at module load."""
    from gke_ray_train_tpu.ops.quant import SERVE_QUANT_KINDS
    return tuple(SERVE_QUANT_KINDS)


def _as_bool(v: Any, field: str) -> bool:
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return bool(v)
    s = str(v).strip().lower()
    if s in ("1", "true", "yes", "on"):
        return True
    if s in ("0", "false", "no", "off", ""):
        return False
    raise PlanError(f"{field}={v!r} is not a boolean")


def _as_int(v: Any, field: str) -> int:
    try:
        return int(v)
    except (TypeError, ValueError):
        raise PlanError(f"{field}={v!r} is not an int") from None


@dataclasses.dataclass(frozen=True)
class ExecutionPlan:
    """The one declarative execution plan. Frozen and hashable by
    fingerprint; every field maps 1:1 to a flat config key
    (:data:`CONFIG_KEYS`) — plancheck PLAN005 keeps that mapping and
    ``config.py`` KNOWN_KEYS from drifting."""

    # -- mesh topology (MeshConfig dialect; -1 = fill) ------------------
    data: int = 1
    fsdp: int = -1
    model: int = 1
    context: int = 1
    pipe: int = 1
    num_slices: int = 1
    pipe_microbatches: int = 0          # 0 = default (one per stage)
    pipe_virtual_stages: int = 1

    # -- batch shape the step compiles against --------------------------
    per_device_batch: int = 2
    grad_accum: int = 1
    max_seq_len: int = 1024
    packing: bool = False

    # -- donation policy ------------------------------------------------
    donate_state: bool = True
    donate_batch: bool = True

    # -- input pipeline --------------------------------------------------
    prefetch: int = 2

    # -- compile-once policy (perf/cache.py) ----------------------------
    compile_cache: bool = True
    compile_cache_dir: Optional[str] = None   # None = perf.cache default
    aot_train_step: bool = True

    # -- runtime guards (analysis/guards.py) ----------------------------
    transfer_guard: Optional[str] = None      # None | "log" | "disallow"
    recompile_limit: int = 0                  # 0 = off
    divergence_guard: bool = False

    # -- serving shape (serve/engine.py) --------------------------------
    # slot count of the continuous-batching engine: every decode
    # executable compiles at exactly [max_batch, 1]
    max_batch: int = 8
    # request length buckets (comma string, normalized ascending): a
    # request lands in the smallest bucket >= prompt_len + max_new, and
    # prefill/decode compile once per bucket. 128-multiples keep the
    # flash-prefill gate (models/kvcache.py) able to engage.
    decode_buckets: str = "256,512"
    # weight quantization the replica serves: "none" | "int8" | "nf4"
    serve_quant: str = "none"
    # multi-tenant adapter slots (serve/adapters.py): the stacked LoRA
    # pool the decode executable compiles against holds max_adapters
    # tenant slots PLUS the reserved zero adapter at slot 0 (= base
    # model), so the pool's leading axis — and with it every serve
    # executable built in pool mode — is shaped by this knob
    max_adapters: int = 8
    # host-side prefix/KV reuse: an identical (bucket, adapter, prompt)
    # re-submission reuses the first request's prefilled KV row + first
    # token through the insert executable instead of re-prefilling.
    # The executable SET is unchanged, but the knob is pinned to the
    # serve compile surface with its siblings so a reuse A/B never
    # shares a sidecar record ambiguously (ISSUE 17 contract).
    prefix_cache: bool = False
    # speculative decoding: "none" (off) | "self" (the target model
    # drafts for itself — the accept-all drill/bench arm) | "distilled"
    # (a separate small draft model handed to the engine). spec_k =
    # draft tokens proposed per round; the fused draft+verify
    # executable compiles its verify forward at [max_batch, spec_k+1].
    spec_draft: str = "none"
    spec_k: int = 4

    # -- observability (obs/) -------------------------------------------
    # unified run telemetry: structured events + metric exports into
    # the run's obs dir (obs/runtime.py resolves OBS_DIR, else
    # <output dir>/obs; unresolvable = off). Operational, never
    # compile-relevant — toggling telemetry must not stale a sidecar.
    obs: bool = True
    obs_dir: Optional[str] = None             # None = derive from run dir
    # anomaly-triggered one-shot jax.profiler captures (obs/capture.py):
    # step-time spike / data stall / recompile / stalled rank, each
    # fires at most once per attempt, bounded by the capture budget
    obs_capture: bool = True
    obs_capture_budget: int = 4
    # causal span tracing (obs/trace.py): per-rank spans-r<N>.jsonl
    # streams under the obs dir — the attempt's ledger-timed boundaries
    # plus serve request lifecycles, merged by `obs report` into a
    # per-attempt critical path. Rides the obs session (OBS=0 disables
    # both); operational like every obs knob — never compile-relevant.
    trace: bool = True

    # -- autotuning (autotune/) -----------------------------------------
    # AUTOTUNE=1 opts the run into overlaying a tuned-plan registry hit
    # (autotune/registry.py, keyed by model-config digest + topology +
    # surface) onto the resolved plan before anything compiles. The
    # FLAG is operational — whether we consulted the registry must not
    # stale a sidecar; the OVERLAY changes compile-relevant fields and
    # re-fingerprints the plan through them, exactly like spelling the
    # tuned values by hand. Excluded from COMPILE_SURFACES like OBS.
    autotune: bool = False
    # AUTOTUNE_INGEST=0 opts an autotuned run OUT of the attempt-end
    # feedback hook (rayint/trainer.py): with obs active, rank 0 of an
    # AUTOTUNE=1 attempt ingests its own observed step times into the
    # registry's observed columns (autotune/registry.py) so
    # `calibrate` can fit the cost model against reality and the drift
    # band can catch a stale entry. Operational like `autotune` itself
    # — excluded from COMPILE_SURFACES.
    autotune_ingest: bool = True

    # -- overlap / fused-kernel execution path (ROADMAP #3) -------------
    # communication/compute overlap mode for the train step:
    #   off    — the plain GSPMD scan (collectives where GSPMD put them)
    #   xla    — same program, compiled with XLA's latency-hiding
    #            scheduler + async-collective flags (TPU backends; the
    #            flags are inert on the CPU mesh, where the program is
    #            bitwise-identical to "off" by construction)
    #   manual — the shard_map microbatch pipeline (train/overlap.py):
    #            layer k+1's FSDP all-gather is double-buffered behind
    #            layer k's compute; bitwise-identical losses to "off",
    #            asserted by test + the BENCH_MODE=overlap A/B
    overlap: str = "off"
    # route the memory-bound epilogue ops through the fused Pallas
    # kernels (ops/fused_norm_rope.py, ops/fused_ce.py) instead of the
    # separate XLA dispatches. Numerics are oracle-pinned in the
    # kernelcheck tolerance ledger, NOT bitwise vs the unfused path
    # (blockwise logsumexp accumulates in a different order).
    fused_ops: bool = False

    # -- DCN-aware gradient sync (parallel/hierarchical.py) -------------
    # cross-slice reduction shape on a multi-slice (num_slices > 1)
    # hybrid mesh, via the manual overlap pipeline:
    #   flat — the full gradient payload crosses the DCN link (GSPMD's
    #          one-flat-all-reduce traffic shape)
    #   hier — intra-slice reduce-scatter → cross-slice all-reduce over
    #          the scattered shard (1/ici_size of the bytes over DCN)
    #          → intra-slice all-gather. Bitwise-identical losses to
    #          flat (both arms share the slice-staged accumulation
    #          grouping); requires overlap="manual" (the hand-placed
    #          collective pipeline) and downgrades LOUDLY to flat on
    #          single-slice plans (no DCN hop to shrink — and the
    #          no-op must not churn the compile fingerprint).
    dcn_sync: str = "flat"
    # "bf16" casts ONLY the hier DCN hop, with error feedback across
    # the grad-accum scan — not bitwise; tolerance-pinned in
    # tests/tolerances/hier_psum.json. Requires dcn_sync="hier".
    dcn_compress: str = "none"

    # -- identity --------------------------------------------------------
    topology: str = "cpu-8"                   # key into CHIP_COUNTS
    budget_preset: Optional[str] = None       # tests/budgets/<name>.json

    def __post_init__(self):
        for axis in MESH_AXES:
            v = getattr(self, axis)
            if v != -1 and v < 1:
                raise PlanError(
                    f"mesh axis {axis}={v} must be >= 1 (or -1 to fill)")
        if self.num_slices < 1:
            raise PlanError(f"num_slices={self.num_slices} must be >= 1")
        for field in ("per_device_batch", "grad_accum", "max_seq_len",
                      "pipe_virtual_stages", "max_adapters", "spec_k"):
            if getattr(self, field) < 1:
                raise PlanError(f"{field}={getattr(self, field)} must "
                                "be >= 1")
        for field in ("prefetch", "recompile_limit", "pipe_microbatches",
                      "obs_capture_budget"):
            if getattr(self, field) < 0:
                raise PlanError(f"{field}={getattr(self, field)} must "
                                "be >= 0")
        if self.transfer_guard not in _TRANSFER_GUARD_MODES:
            raise PlanError(
                f"transfer_guard={self.transfer_guard!r} not in "
                f"{_TRANSFER_GUARD_MODES}")
        if self.max_batch < 1:
            raise PlanError(f"max_batch={self.max_batch} must be >= 1")
        self.bucket_list()   # validates decode_buckets
        if self.serve_quant not in _serve_quant_kinds():
            raise PlanError(f"serve_quant={self.serve_quant!r} not in "
                            f"{_serve_quant_kinds()}")
        if self.spec_draft not in SPEC_DRAFT_MODES:
            raise PlanError(f"spec_draft={self.spec_draft!r} not in "
                            f"{SPEC_DRAFT_MODES}")
        if self.overlap not in OVERLAP_MODES:
            raise PlanError(f"overlap={self.overlap!r} not in "
                            f"{OVERLAP_MODES}")
        if self.dcn_sync not in DCN_SYNC_MODES:
            raise PlanError(f"dcn_sync={self.dcn_sync!r} not in "
                            f"{DCN_SYNC_MODES}")
        if self.dcn_compress not in DCN_COMPRESS_MODES:
            raise PlanError(f"dcn_compress={self.dcn_compress!r} not in "
                            f"{DCN_COMPRESS_MODES}")
        if self.dcn_sync == "hier" and self.num_slices <= 1:
            # LOUD no-op downgrade, not a refusal: an elastic replan
            # that collapses a 2-slice pool to one slice must keep its
            # DCN_SYNC=hier env without dying — but the downgraded plan
            # must fingerprint IDENTICALLY to flat (hier on one slice
            # compiles the same program; a phantom fingerprint split
            # would stale sidecars for nothing). Pinned by test.
            import logging
            logging.getLogger(__name__).warning(
                "DCN_SYNC=hier on a single-slice plan (num_slices=1) is "
                "a no-op — downgrading to flat (no DCN hop to shrink)")
            object.__setattr__(self, "dcn_sync", "flat")
            if self.dcn_compress != "none":
                logging.getLogger(__name__).warning(
                    "DCN_COMPRESS=%s downgraded to none with it (it "
                    "compresses the hier DCN hop)", self.dcn_compress)
                object.__setattr__(self, "dcn_compress", "none")
        if self.dcn_sync == "hier" and self.overlap != "manual":
            raise PlanError(
                "dcn_sync='hier' needs overlap='manual' — the "
                "hierarchical reduction is hand-placed by the manual "
                "shard_map pipeline (train/overlap.py); GSPMD's own "
                "gradient all-reduce cannot be decomposed from outside")
        if self.dcn_compress != "none" and self.dcn_sync != "hier":
            raise PlanError(
                f"dcn_compress={self.dcn_compress!r} compresses the "
                "hier cross-slice hop; set DCN_SYNC=hier (compressing "
                "a full-payload flat hop is not supported)")
        if self.overlap == "manual":
            # the manual pipeline hand-places the fsdp collectives; the
            # structural axes would need their own manual collectives
            # (TP all-reduces, ring permutes, stage pipelining) that
            # the shard_map path does not emit — refuse loudly instead
            # of silently computing wrong. A -1 fill is resolved
            # against the declared topology first: model=-1 that fills
            # to 1 IS a data/fsdp mesh (an unresolvable fill keeps the
            # raw value and is refused — better loud than wrong).
            try:
                sizes = self.resolved_sizes()
            except (ValueError, IndexError, KeyError):
                # unresolvable fill or a bogus topology (whose own
                # validation error follows below)
                sizes = {a: getattr(self, a) for a in MESH_AXES}
            for axis in ("model", "context", "pipe"):
                if sizes[axis] != 1:
                    raise PlanError(
                        f"overlap='manual' supports data/fsdp meshes "
                        f"only; {axis}={sizes[axis]} — use "
                        "overlap='xla' (latency-hiding scheduler) on "
                        "structural-axis topologies")
        if self.topology not in CHIP_COUNTS:
            fam, _, count = self.topology.partition("-")
            if fam not in TOPOLOGY_FAMILIES or not count.isdigit() \
                    or int(count) < 1:
                raise PlanError(
                    f"topology={self.topology!r} unknown; presets: "
                    f"{sorted(CHIP_COUNTS)} (or <family>-<chips> with "
                    f"family in {TOPOLOGY_FAMILIES} — the elastic-replan "
                    "dialect for non-preset survivor pools)")

    # ------------------------------------------------------------------
    # dialect constructors
    # ------------------------------------------------------------------

    @staticmethod
    def axis_names() -> Tuple[str, ...]:
        """The mesh-axis vocabulary — the single source shardlint TPU002
        reads (it used to parse ``parallel/mesh.py`` source)."""
        return tuple(MESH_AXES)

    @classmethod
    def from_config(cls, config: Mapping[str, Any]) -> "ExecutionPlan":
        """Build from the flat UPPER_CASE dialect (fine_tune_config.json
        / env-var strings). Unknown keys are ignored here — ``config.py
        audit_config`` owns unknown-key warnings; plancheck PLAN005 owns
        plan↔KNOWN_KEYS drift."""
        kw: Dict[str, Any] = {}
        for field, key in CONFIG_KEYS.items():
            if key in config and config[key] is not None:
                kw[field] = _coerce(field, config[key])
        return cls(**kw)

    @classmethod
    def from_env(cls, env: Optional[Mapping[str, str]] = None
                 ) -> "ExecutionPlan":
        """Build from environment variables (the dialect the trainer
        forwards to Ray workers). Same keys as the JSON dialect."""
        return cls.from_config(dict(env if env is not None
                                    else os.environ))

    @classmethod
    def resolve(cls, config: Optional[Mapping[str, Any]] = None,
                env: Optional[Mapping[str, str]] = None,
                **overrides: Any) -> "ExecutionPlan":
        """The runtime constructor: env dialect overlaid by the config
        dialect (config key wins — the same precedence every legacy
        knob had), then pythonic kwarg overrides. This is what the
        trainer and both entry points call, so the plan a worker runs
        is derived from exactly the sources the legacy dialects read."""
        merged: Dict[str, Any] = dict(env if env is not None
                                      else os.environ)
        for k, v in (config or {}).items():
            if v is not None:
                merged[k] = v
        plan = cls.from_config(merged)
        if overrides:
            plan = dataclasses.replace(
                plan, **{k: _coerce(k, v) for k, v in overrides.items()})
        return plan

    @classmethod
    def from_kwargs(cls, **kwargs: Any) -> "ExecutionPlan":
        """Build from pythonic field names (the ``run_training`` /
        ``make_train_step`` kwargs dialect)."""
        unknown = sorted(set(kwargs) - {f.name for f in
                                        dataclasses.fields(cls)})
        if unknown:
            raise PlanError(f"unknown plan fields {unknown}; valid: "
                            f"{sorted(f.name for f in dataclasses.fields(cls))}")
        return cls(**{k: _coerce(k, v) for k, v in kwargs.items()})

    def to_config(self) -> Dict[str, Any]:
        """The plan in the flat UPPER_CASE dialect (round-trips through
        :meth:`from_config`)."""
        return {key: getattr(self, field)
                for field, key in CONFIG_KEYS.items()}

    # ------------------------------------------------------------------
    # identity
    # ------------------------------------------------------------------

    def canonical(self) -> Dict[str, Any]:
        """JSON-safe canonical field dict — the fingerprint payload.
        ``obs_dir`` is excluded: it is a RUN-scoped scratch/output path
        (record_baselines points it at a mktemp dir), and two runs of
        the byte-identical plan must fingerprint identically or the
        stable identity budget JSONs / BENCH records / attempt logs
        correlate on dissolves into per-run noise."""
        return {f.name: getattr(self, f.name)
                for f in dataclasses.fields(self)
                if f.name != "obs_dir"}

    def fingerprint(self, surface: Optional[str] = None) -> str:
        """Stable 16-hex-char identity of the declared plan — every
        field except the run-scoped ``obs_dir`` path (see
        :meth:`canonical`). Recorded in budget JSONs, BENCH records,
        attempt logs.

        ``surface="train"|"serve"`` narrows the identity to that
        surface's compile-relevant fields (delegates to
        :meth:`compile_fingerprint`) — the per-surface identity AOT
        sidecars key on, so serve-only knobs (``MAX_BATCH`` /
        ``DECODE_BUCKETS`` / ``SERVE_QUANT``) never churn TRAIN
        sidecars and vice versa."""
        if surface is not None:
            return self.compile_fingerprint(surface)
        return hashlib.sha256(
            json.dumps(self.canonical(), sort_keys=True).encode()
        ).hexdigest()[:16]

    def compile_fingerprint(self, surface: str = "train") -> str:
        """Identity of the COMPILED PROGRAM the plan implies for one
        compile *surface*: the mesh fields plus that surface's own
        program-shaping fields (:data:`COMPILE_SURFACES`). This is what
        AOT sidecar keys and compile-cache subdirs embed (composed with
        the runtime topology fingerprint, which supplies device
        kind/count) — toggling an operational knob (prefetch depth, a
        guard, the cache dir itself) must NOT invalidate a
        bitwise-identical executable, and the OTHER surface's fields
        must not either: retuning ``DECODE_BUCKETS`` on a serving
        replica must not stale the training job's sidecar.
        ``surface="all"`` hashes the union (the PLAN004 comparison
        domain)."""
        try:
            fields = COMPILE_SURFACES[surface]
        except KeyError:
            raise PlanError(f"surface={surface!r} not in "
                            f"{sorted(COMPILE_SURFACES)}") from None
        payload: Dict[str, Any] = {"surface": surface}
        payload.update({f: getattr(self, f) for f in fields})
        return hashlib.sha256(
            json.dumps(payload, sort_keys=True).encode()).hexdigest()[:16]

    # ------------------------------------------------------------------
    # derived topology / shardings
    # ------------------------------------------------------------------

    @property
    def chips(self) -> int:
        if self.topology in CHIP_COUNTS:
            return CHIP_COUNTS[self.topology]
        # validated "<family>-<n>" non-preset shape (elastic replan)
        return int(self.topology.split("-", 1)[1])

    def mesh_config(self) -> MeshConfig:
        return MeshConfig(data=self.data, fsdp=self.fsdp, model=self.model,
                          context=self.context, pipe=self.pipe,
                          num_slices=self.num_slices)

    def resolved_sizes(self, n_chips: Optional[int] = None
                       ) -> Dict[str, int]:
        """Mesh axis sizes with -1 resolved against ``n_chips`` (default:
        the declared topology's chip count). Raises ValueError when the
        plan cannot tile that chip count."""
        resolved = self.mesh_config().resolve(
            self.chips if n_chips is None else n_chips)
        return {axis: getattr(resolved, axis) for axis in MESH_AXES}

    def build_mesh(self, devices=None):
        """The concrete device mesh (the one runtime-facing method)."""
        from gke_ray_train_tpu.parallel.mesh import build_mesh
        return build_mesh(self.mesh_config(), devices)

    @property
    def context_sharded(self) -> bool:
        """Whether batch sequences shard over the context axis. A
        declared ``-1`` (fill) is resolved against the declared
        topology first — the DECLARED value alone would report
        unsharded for a context axis that fills to >1."""
        if self.context == -1:
            try:
                return self.resolved_sizes()["context"] > 1
            except ValueError:
                return True   # unresolvable fill: assume sharded
        return self.context > 1

    def batch_spec(self):
        """Logical PartitionSpec of a [batch, seq, ...] array."""
        from jax.sharding import PartitionSpec as P
        return P(BATCH_AXES,
                 "context" if self.context_sharded else None)

    def bucket_list(self) -> Tuple[int, ...]:
        """``decode_buckets`` parsed to ascending unique ints — the
        lengths the serving engine compiles prefill/decode pairs for."""
        try:
            vals = tuple(sorted({int(tok) for tok in
                                 str(self.decode_buckets).split(",")
                                 if str(tok).strip()}))
        except ValueError:
            raise PlanError(
                f"decode_buckets={self.decode_buckets!r} is not a "
                "comma-separated int list") from None
        if not vals or any(v < 1 for v in vals):
            raise PlanError(f"decode_buckets={self.decode_buckets!r} "
                            "must name at least one length >= 1")
        return vals

    def batch_keys(self) -> Tuple[str, ...]:
        return ("inputs", "targets", "weights") + (
            ("segment_ids", "positions") if self.packing else ())

    def batch_shardings(self, mesh) -> Dict[str, Any]:
        from gke_ray_train_tpu.train.step import batch_shardings
        return batch_shardings(mesh, self.batch_keys(),
                               context_sharded=self.context_sharded)

    def logical_param_specs(self, model_cfg) -> Any:
        """The canonical per-leaf PartitionSpec tree (delegates to
        ``models/transformer.py`` — the plan exposes, never forks, the
        logical spec)."""
        from gke_ray_train_tpu.models.transformer import param_specs
        return param_specs(model_cfg)

    def abstract_params(self, model_cfg) -> Any:
        """Shape/dtype pytree of the params via ``jax.eval_shape`` —
        no weights materialized, no backend touched."""
        import jax
        import jax.numpy as jnp

        from gke_ray_train_tpu.models.transformer import init_params
        key = jax.ShapeDtypeStruct((2,), jnp.uint32)  # legacy raw key
        return jax.eval_shape(lambda k: init_params(model_cfg, k), key)

    def donate_argnums(self) -> Tuple[int, ...]:
        if self.donate_state and self.donate_batch:
            return (0, 1)
        return (0,) if self.donate_state else ()

    def runtime_guards(self):
        """The resolved guard bundle ``run_training`` consumes."""
        from gke_ray_train_tpu.analysis.guards import RuntimeGuards
        return RuntimeGuards(transfer_mode=self.transfer_guard,
                             divergence=self.divergence_guard)

    def global_batch(self, n_chips: Optional[int] = None) -> int:
        sizes = self.resolved_sizes(n_chips)
        return (self.per_device_batch * sizes["data"] * sizes["fsdp"]
                * self.grad_accum)

    # ------------------------------------------------------------------
    # static feasibility (the arithmetic plancheck builds on)
    # ------------------------------------------------------------------

    def mesh_findings(self, n_chips: Optional[int] = None) -> List[str]:
        """Topology feasibility: every axis size tiles the chip count."""
        n = self.chips if n_chips is None else n_chips
        try:
            self.resolved_sizes(n)
        except ValueError as e:
            return [f"mesh {{{', '.join(f'{a}={getattr(self, a)}' for a in MESH_AXES)}}} "
                    f"does not tile {n} chips ({self.topology if n_chips is None else n}): {e}"]
        return []

    def model_findings(self, model_cfg,
                       n_chips: Optional[int] = None) -> List[str]:
        """Model-dim divisibility against the resolved mesh: every
        sharded dim of every param leaf (embed, heads, mlp, vocab, the
        stacked-layer pipe dim) must divide the product of the axes its
        logical PartitionSpec names — plus the activation-level
        head/sequence constraints the leaf shapes alone cannot see."""
        import jax
        from jax.sharding import PartitionSpec as P

        out = self.mesh_findings(n_chips)
        if out:
            return out
        sizes = self.resolved_sizes(n_chips)

        def axes_size(entry) -> Tuple[int, Tuple[str, ...]]:
            names = (entry if isinstance(entry, (tuple, list))
                     else (entry,)) if entry is not None else ()
            prod = 1
            for a in names:
                prod *= sizes[a]
            return prod, tuple(names)

        specs = self.logical_param_specs(model_cfg)
        shapes = self.abstract_params(model_cfg)
        spec_leaves = jax.tree_util.tree_leaves_with_path(
            specs, is_leaf=lambda x: isinstance(x, P))
        shape_map = {jax.tree_util.keystr(p): s.shape
                     for p, s in jax.tree_util.tree_leaves_with_path(shapes)}
        for path, spec in spec_leaves:
            name = jax.tree_util.keystr(path)
            shape = shape_map.get(name)
            if shape is None:
                continue
            for d, entry in enumerate(spec):
                prod, names = axes_size(entry)
                if prod > 1 and shape[d] % prod != 0:
                    out.append(
                        f"param {name} dim {d} (size {shape[d]}) is not "
                        f"divisible by mesh axes {names} "
                        f"(size {prod}) on {n_chips or self.topology}")
        # activation-level constraints
        if sizes["model"] > 1:
            for field in ("n_heads", "n_kv_heads"):
                heads = getattr(model_cfg, field)
                if heads % sizes["model"] != 0:
                    out.append(
                        f"{field}={heads} is not divisible by the model "
                        f"axis (size {sizes['model']}) — attention heads "
                        "cannot tile the tensor-parallel axis")
        if sizes["context"] > 1 and self.max_seq_len % sizes["context"]:
            out.append(
                f"max_seq_len={self.max_seq_len} is not divisible by the "
                f"context axis (size {sizes['context']})")
        if sizes["pipe"] > 1:
            depth = model_cfg.n_repeats
            if depth % (sizes["pipe"] * self.pipe_virtual_stages):
                out.append(
                    f"n_repeats={depth} is not divisible by pipe axis x "
                    f"virtual stages ({sizes['pipe']} x "
                    f"{self.pipe_virtual_stages})")
        return out

    def feasibility(self, model_cfg=None,
                    n_chips: Optional[int] = None) -> List[str]:
        """All static findings for one topology (mesh + model dims)."""
        if model_cfg is None:
            return self.mesh_findings(n_chips)
        return self.model_findings(model_cfg, n_chips)


# ---------------------------------------------------------------------------
# field <-> flat-config-key mapping (the dialect bridge; PLAN005 checks
# it against config.py's KNOWN_KEYS in both directions)
# ---------------------------------------------------------------------------

CONFIG_KEYS: Dict[str, str] = {
    "data": "MESH_DATA",
    "fsdp": "MESH_FSDP",
    "model": "MESH_MODEL",
    "context": "MESH_CONTEXT",
    "pipe": "MESH_PIPE",
    "num_slices": "NUM_SLICES",
    "pipe_microbatches": "PIPE_MICROBATCHES",
    "pipe_virtual_stages": "PIPE_VIRTUAL_STAGES",
    "per_device_batch": "PER_DEVICE_TRAIN_BATCH_SIZE",
    "grad_accum": "GRADIENT_ACCUMULATION_STEPS",
    "max_seq_len": "MAX_SEQ_LENGTH",
    "packing": "PACKING",
    "donate_state": "DONATE_STATE",
    "donate_batch": "DONATE_BATCH",
    "prefetch": "PREFETCH_BATCHES",
    "compile_cache": "COMPILE_CACHE",
    "compile_cache_dir": "COMPILE_CACHE_DIR",
    "aot_train_step": "AOT_TRAIN_STEP",
    "transfer_guard": "TRANSFER_GUARD",
    "recompile_limit": "RECOMPILE_LIMIT",
    "divergence_guard": "DIVERGENCE_GUARD",
    "max_batch": "MAX_BATCH",
    "decode_buckets": "DECODE_BUCKETS",
    "serve_quant": "SERVE_QUANT",
    "max_adapters": "MAX_ADAPTERS",
    "prefix_cache": "PREFIX_CACHE",
    "spec_draft": "SPEC_DRAFT",
    "spec_k": "SPEC_K",
    "obs": "OBS",
    "obs_dir": "OBS_DIR",
    "obs_capture": "OBS_CAPTURE",
    "obs_capture_budget": "OBS_CAPTURE_BUDGET",
    "trace": "TRACE",
    "autotune": "AUTOTUNE",
    "autotune_ingest": "AUTOTUNE_INGEST",
    "overlap": "OVERLAP",
    "fused_ops": "FUSED_OPS",
    "dcn_sync": "DCN_SYNC",
    "dcn_compress": "DCN_COMPRESS",
    "topology": "TOPOLOGY",
    "budget_preset": "BUDGET_PRESET",
}

# the fields that determine a COMPILED PROGRAM, split by compile
# surface. The mesh fields shape every program; the train-only fields
# shape the train/eval step; the serve-only fields shape the engine's
# prefill/decode/insert executables. compile_fingerprint(surface)
# hashes mesh + that surface's own fields, so a serve-knob retune
# (MAX_BATCH, DECODE_BUCKETS, SERVE_QUANT) no longer stales TRAIN AOT
# sidecars — the PR 7 tradeoff, removed. plancheck's PLAN004
# budget-compatibility rule compares the union (COMPILE_RELEVANT_
# FIELDS) — a budget pins one exact program on both surfaces.
_MESH_COMPILE_FIELDS: Tuple[str, ...] = (
    "data", "fsdp", "model", "context", "pipe", "num_slices")
_TRAIN_ONLY_COMPILE_FIELDS: Tuple[str, ...] = (
    "pipe_microbatches", "pipe_virtual_stages",
    "per_device_batch", "grad_accum", "max_seq_len", "packing",
    "donate_state", "donate_batch",
    # overlap rewrites the step's collective schedule (manual: a
    # different program; xla: different compiler flags on the same
    # program) and fused_ops swaps epilogue dispatches for Pallas
    # kernels — both change the compiled train executable, so sidecars
    # recorded under a different setting must stale (the OBS twin of
    # this pin asserts the opposite: telemetry knobs are EXCLUDED).
    # dcn_sync/dcn_compress reshape the manual pipeline's reduction
    # collectives the same way — train-surface only (a serving replica
    # decodes mesh-local; retuning the gradient sync must not stale
    # serve sidecars — pinned by test like the OBS exclusion twin)
    "overlap", "fused_ops", "dcn_sync", "dcn_compress")
_SERVE_ONLY_COMPILE_FIELDS: Tuple[str, ...] = (
    "max_batch", "decode_buckets", "serve_quant",
    # multi-tenant + speculative serving (ISSUE 17): max_adapters
    # shapes the stacked adapter pool's leading axis, spec_draft/spec_k
    # shape the fused draft+verify executable, and prefix_cache rides
    # the serve surface with them — all serve-only, so retuning any of
    # them can never stale a TRAIN sidecar
    "max_adapters", "prefix_cache", "spec_draft", "spec_k")
COMPILE_RELEVANT_FIELDS: Tuple[str, ...] = (
    _MESH_COMPILE_FIELDS + _TRAIN_ONLY_COMPILE_FIELDS
    + _SERVE_ONLY_COMPILE_FIELDS)
COMPILE_SURFACES: Dict[str, Tuple[str, ...]] = {
    "train": _MESH_COMPILE_FIELDS + _TRAIN_ONLY_COMPILE_FIELDS,
    "serve": _MESH_COMPILE_FIELDS + _SERVE_ONLY_COMPILE_FIELDS,
    "all": COMPILE_RELEVANT_FIELDS,
}


# ---------------------------------------------------------------------------
# elastic replan: re-resolve a plan against a changed device pool
# ---------------------------------------------------------------------------

def replan(plan: ExecutionPlan, n_devices: int, *, model_cfg=None,
           preserve_global_batch: bool = True) -> ExecutionPlan:
    """The elastic-resume half of PLAN003's promise: given a plan and
    the SURVIVING device count (a slice evicted, a spot pool shrunk, a
    node returned), pick the largest feasible axis assignment on the
    new pool.

    Rules (the same reshard dialect plancheck's portability matrix
    statically validates):

    - the *structural* axes (model, context, pipe) are NEVER reflowed —
      they change the compiled program and the logical layout; a pool
      that cannot tile them is a :class:`PlanError` (a PLAN001-class
      rejection, surfaced, not crashed);
    - only data/fsdp reflow, preferring the assignment closest to the
      declared data:fsdp ratio (ties: larger fsdp — params keep
      sharding);
    - ``num_slices`` shrinks proportionally when the eviction removed
      whole slices, else collapses to 1;
    - the global batch is preserved by default (``per_device_batch``
      scales inversely with the data-parallel width when it divides
      evenly) so the optimization trajectory survives the reshard;
    - the declared topology is re-pinned to ``<family>-<n_devices>``
      and a pinned ``budget_preset`` is dropped — the recorded budget
      describes the OLD mesh's program and would trip PLAN004 as a
      false drift signal;
    - every candidate is validated (PLAN001 arithmetic, and PLAN002
      model-dim divisibility when ``model_cfg`` is given); an
      infeasible pool raises :class:`PlanError` carrying the findings.

    ``replan(plan, plan.chips)`` is the identity — recovery to the
    full shape is the same call, at the attempt where the pool grew
    back.
    """
    import math

    if n_devices < 1:
        raise PlanError(f"replan: n_devices={n_devices} must be >= 1")
    # a tuned-plan overlay (autotune/registry.py) is keyed by the
    # topology it was searched on — a plan tuned for 8 devices silently
    # riding a 4-device attempt is a correctness trap. Drop it the same
    # way the stale BUDGET_PRESET pin is dropped below: replan from the
    # PRE-overlay plan, and let the caller's maybe_apply re-key the
    # registry lookup against the survivors' topology (usually a miss).
    tuned_base = getattr(plan, "_tuned_base", None)
    if tuned_base is not None and n_devices != plan.chips:
        import logging
        logging.getLogger(__name__).warning(
            "replan: dropping tuned-plan overlay %s (tuned for %s; "
            "pool is %d devices) — the registry re-keys on the new "
            "topology", getattr(plan, "_tuned_key", "<unkeyed>"),
            plan.topology, n_devices)
        plan = tuned_base
    try:
        base = plan.resolved_sizes()
    except ValueError as e:
        raise PlanError("replan: the declared plan does not tile its "
                        f"own topology: {e}") from None
    if n_devices == plan.chips:
        return plan
    structural = base["model"] * base["context"] * base["pipe"]
    if n_devices % structural:
        raise PlanError(
            f"replan: {n_devices} surviving devices cannot tile the "
            f"structural axes (model={base['model']} x "
            f"context={base['context']} x pipe={base['pipe']} = "
            f"{structural}); structural axes are never reflowed — only "
            "data/fsdp")
    remaining = n_devices // structural
    global_rows = plan.per_device_batch * base["data"] * base["fsdp"]
    ratio0 = math.log(base["data"] / base["fsdp"])
    candidates = sorted(
        ((d, remaining // d) for d in range(1, remaining + 1)
         if remaining % d == 0),
        key=lambda df: (abs(math.log(df[0] / df[1]) - ratio0), -df[1]))
    # whole-slice evictions keep the DCN layout; anything else
    # collapses to one slice (the data axis no longer tiles slices)
    if plan.num_slices > 1 and \
            (plan.num_slices * n_devices) % plan.chips == 0:
        surviving_slices = max(plan.num_slices * n_devices
                               // plan.chips, 1)
    else:
        surviving_slices = 1
    family = plan.topology.split("-", 1)[0]
    rejections: List[str] = []
    for data, fsdp in candidates:
        slices = surviving_slices if data % surviving_slices == 0 else 1
        pdb = plan.per_device_batch
        if preserve_global_batch and global_rows % (data * fsdp) == 0:
            pdb = max(global_rows // (data * fsdp), 1)
        cand = dataclasses.replace(
            plan, data=data, fsdp=fsdp, num_slices=slices,
            per_device_batch=pdb, topology=f"{family}-{n_devices}",
            budget_preset=None)
        findings = cand.feasibility(model_cfg)
        if not findings:
            return cand
        rejections.extend(f"data={data} fsdp={fsdp}: {m}"
                          for m in findings[:2])
    raise PlanError(
        f"replan: no feasible data/fsdp assignment on {n_devices} "
        f"devices (structural axes model={base['model']} "
        f"context={base['context']} pipe={base['pipe']} kept): "
        + "; ".join(rejections[:6]))

# plan knobs the trainer forwards from the driver env to Ray workers
# (rayint/trainer.py) — derived from the mapping so a renamed knob
# cannot silently stop being forwarded
ENV_FORWARD_KEYS: Tuple[str, ...] = tuple(sorted(
    CONFIG_KEYS[f] for f in (
        "compile_cache", "compile_cache_dir", "aot_train_step",
        "transfer_guard", "recompile_limit", "divergence_guard",
        "prefetch",
        # obs telemetry knobs ride to the workers the same way (a
        # driver-side `env OBS_DIR=...` must shape every rank's stream)
        "obs", "obs_dir", "obs_capture", "obs_capture_budget", "trace",
        # a driver-side `env OVERLAP=manual` / `FUSED_OPS=1` A/B must
        # shape the program every worker compiles — and so must the
        # DCN gradient-sync arms (`env DCN_SYNC=hier DCN_COMPRESS=bf16`)
        "overlap", "fused_ops", "dcn_sync", "dcn_compress",
        # a driver-side `env AUTOTUNE=1` must reach every worker's
        # registry lookup (autotune/registry.py) — and AUTOTUNE_INGEST
        # its attempt-end observed-row feedback hook
        "autotune", "autotune_ingest")))

_BOOL_FIELDS = frozenset({"packing", "donate_state", "donate_batch",
                          "compile_cache", "aot_train_step",
                          "divergence_guard", "obs", "obs_capture",
                          "trace", "fused_ops", "autotune",
                          "autotune_ingest", "prefix_cache"})
_INT_FIELDS = frozenset({"data", "fsdp", "model", "context", "pipe",
                         "num_slices", "pipe_microbatches",
                         "pipe_virtual_stages", "per_device_batch",
                         "grad_accum", "max_seq_len", "prefetch",
                         "recompile_limit", "max_batch",
                         "obs_capture_budget", "max_adapters",
                         "spec_k"})


def _coerce(field: str, value: Any) -> Any:
    """One coercion path for all three dialects: JSON values, env-var
    strings, and python kwargs normalize to the same field types, so
    the fingerprints agree."""
    if field in _BOOL_FIELDS:
        return _as_bool(value, field)
    if field in _INT_FIELDS:
        return _as_int(value, field)
    if field == "transfer_guard":
        v = (str(value).strip().lower() if value is not None else None)
        if v in ("", "0", "off", "false", "allow", None):
            return None
        return v
    if field in ("compile_cache_dir", "budget_preset", "obs_dir"):
        return str(value) if value is not None else None
    if field == "topology":
        return str(value).strip().lower()
    if field == "decode_buckets":
        # JSON lists, "512,256" strings and bare ints all normalize to
        # one canonical ascending comma string, so the three dialects
        # fingerprint identically
        toks = (value if isinstance(value, (list, tuple))
                else str(value).split(","))
        try:
            vals = sorted({int(str(t).strip()) for t in toks
                           if str(t).strip()})
        except ValueError:
            raise PlanError(f"decode_buckets={value!r} is not a "
                            "comma-separated int list") from None
        return ",".join(str(v) for v in vals)
    if field in ("serve_quant", "spec_draft"):
        # "", "0", "false" and "off" all spell the disabled arm — the
        # env dialect needs a disabling spelling (`env SPEC_DRAFT=`)
        v = str(value).strip().lower()
        return "none" if v in ("", "0", "false", "no", "off") else v
    if field == "overlap":
        # "", "0" and "false" all mean the plain scan — the env dialect
        # needs a disabling spelling (`env OVERLAP= python ...`)
        v = str(value).strip().lower()
        return "off" if v in ("", "0", "false", "no") else v
    if field == "dcn_sync":
        v = str(value).strip().lower()
        return "flat" if v in ("", "0", "false", "no", "off") else v
    if field == "dcn_compress":
        v = str(value).strip().lower()
        return "none" if v in ("", "0", "false", "no", "off") else v
    return value


# ---------------------------------------------------------------------------
# the one compile surface (the SNIPPETS compile_step_with_plan shape)
# ---------------------------------------------------------------------------

def compile_step_with_plan(plan: ExecutionPlan, mesh, fn: Callable,
                           *abstract_args: Any,
                           in_shardings: Any = None,
                           out_shardings: Any = None,
                           donate_argnums: Optional[Tuple[int, ...]] = None,
                           sidecar: Optional[str] = None,
                           label: str = "train_step",
                           surface: str = "train") -> Callable:
    """Compile a step function under one plan — the single surface
    training, bench, and analysis all route through.

    ``fn`` may be a plain python step body (jitted here with the plan's
    donation policy and any explicit in/out shardings — PartitionSpec
    trees are resolved against ``mesh`` into NamedShardings) or an
    already-jitted function (left as is). When ``abstract_args`` are
    given, the plan's AOT/compile-cache policy applies: the step is
    built ahead of time via ``jit(...).lower(...).compile()`` (hitting
    the persistent cache when warm) and — when ``sidecar`` is set and
    ``plan.aot_train_step`` — serialized beside the checkpoint under a
    key that embeds ``plan.compile_fingerprint(surface)``, so a sidecar
    recorded under a plan that compiles a DIFFERENT program is stale by
    construction (operational knobs don't invalidate it, and neither do
    the OTHER surface's fields — serving knobs don't churn train
    sidecars; the engine passes ``surface="serve"``).
    """
    import jax

    if not hasattr(fn, "lower"):        # plain body → jit under the plan
        kw: Dict[str, Any] = {}
        if in_shardings is not None or out_shardings is not None:
            if in_shardings is None or out_shardings is None:
                raise PlanError(
                    "compile_step_with_plan needs BOTH in_shardings and "
                    "out_shardings (or neither — GSPMD propagates from "
                    "the plan-sharded arguments)")
            if mesh is not None:
                # logical PartitionSpec leaves → concrete NamedShardings
                # (already-concrete sharding leaves pass through)
                from jax.sharding import NamedSharding, PartitionSpec

                def concretize(tree):
                    return jax.tree.map(
                        lambda s: NamedSharding(mesh, s)
                        if isinstance(s, PartitionSpec) else s,
                        tree,
                        is_leaf=lambda x: isinstance(
                            x, (PartitionSpec, NamedSharding)))

                in_shardings = concretize(in_shardings)
                out_shardings = concretize(out_shardings)
            kw.update(in_shardings=in_shardings,
                      out_shardings=out_shardings)
        argnums = (plan.donate_argnums() if donate_argnums is None
                   else tuple(donate_argnums))
        opts = overlap_compiler_options(plan)
        if opts is not None:
            # overlap="xla" on a TPU backend: the latency-hiding
            # scheduler flags ride the jit params into every
            # lower().compile() of this step. A backend that refuses a
            # flag fails at compile time — fall back to plain flags
            # there rather than here (the refusal message names the
            # flag; swallowing it pre-compile would hide WHICH one).
            kw["compiler_options"] = opts
        fn = jax.jit(fn, donate_argnums=argnums, **kw)
        try:
            fn.donate_argnums = argnums
        except (AttributeError, TypeError):  # pragma: no cover
            pass
    if not abstract_args or not plan.aot_train_step:
        # AOT disabled by the plan: the plain jitted step (first call
        # traces+compiles, hitting the persistent cache when warm)
        return fn
    from gke_ray_train_tpu.perf.cache import build_or_load_step
    return build_or_load_step(fn, *abstract_args, sidecar=sidecar,
                              label=label, plan=plan, surface=surface)
