#!/usr/bin/env bash
# Record the BASELINE.md measurement set on the attached TPU chip.
# Each line of bench output is one JSON record; copy the numbers into
# BASELINE.md with the exact command that produced them.
#
# Usage: bash scripts/record_baselines.sh [outfile]
set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${1:-/tmp/baselines_$(date +%s).jsonl}"

run() {
  local label="$1"; shift
  echo "== $label: $*" | tee -a "$OUT.log"
  if timeout 1800 "$@" >> "$OUT" 2>> "$OUT.log"; then
    tail -1 "$OUT"
  else
    # JSON-shaped marker: $OUT stays line-parseable AND failed runs
    # (possibly with partial records above) are flagged in-band
    # leading newline: a SIGTERM'd bench can leave $OUT mid-line
    printf '\n{"failed": "%s", "log": "%s"}\n' "$label" "$OUT.log" | tee -a "$OUT" >&2
  fi
}

# driver-identical default (0.69B proxy, full remat) + the dots A/B
run proxy-full  python bench.py
run proxy-dots  env BENCH_REMAT=dots python bench.py

# BASELINE.json configs at full family dims on one chip
run qlora8b        env BENCH_MODE=qlora8b python bench.py
run mistral7b-lora env BENCH_MODE=mistral7b-lora python bench.py
run gemma2-4k      env BENCH_MODE=gemma2-4k python bench.py
run seq4k          env BENCH_MODE=seq4k python bench.py
run moe            env BENCH_MODE=moe python bench.py
run qwen2-lora     env BENCH_MODE=qwen2-lora python bench.py
run decode         env BENCH_MODE=decode python bench.py

# continuous-batching serving A/B (serve/engine.py): engine across
# MAX_BATCH slots vs serial batch-1 greedy over the same request set,
# + p50/p99 per-token latency, batch occupancy, decode StepCostReport.
# The same run records the multi-tenant arm (mixed batched-LoRA batch
# vs per-adapter serial engines — bitwise, recompile-free, >=1.3x
# asserted, pool hit/miss/evict counters) and the speculative arm
# (self-draft SPEC_K=4 vs plain — bitwise, iteration reduction +
# acceptance rate)
run serve          env BENCH_MODE=serve python bench.py

# overlap execution path A/B (train/overlap.py, plan knob OVERLAP):
# OVERLAP=off vs =manual through the real make_train_step — the record
# asserts bitwise-identical loss streams and carries each arm's
# scheduled-HLO overlap evidence (overlap_frac / exposed collective
# bytes), the half of the claim that survives a dead backend
run overlap        env BENCH_MODE=overlap python bench.py

# DCN gradient-sync A/B (parallel/hierarchical.py, plan knobs
# DCN_SYNC/DCN_COMPRESS) on the emulated 2-slice hybrid mesh (re-execs
# onto the canonical 8-fake-device CPU mesh): flat vs hier cross-slice
# reduction — the record asserts bitwise-identical loss streams and
# carries each arm's ici_bytes/dcn_bytes/overlap_frac; value = the
# DCN traffic shrink factor (~= ici_size)
run dcn            env BENCH_MODE=dcn python bench.py

# autotune default-vs-tuned A/B (autotune/, re-execs onto the canonical
# 8-fake-device CPU mesh): cost-model search over the tiny_fsdp8 base
# plan; the record carries the winner diff, per-arm StepCostReport +
# exposed bytes + plan fingerprints, and both arms' real loss streams
# (tuned trajectory asserted valid against the default's shape);
# value = modeled step-time improvement
run autotune       env BENCH_MODE=autotune python bench.py

# fault-tolerance drill: time-to-recover (injected kill -> first
# post-resume step) + checkpoint-save latency under SIGTERM (must fit
# the preemption grace window); the record splits recompile time from
# restore+fast-forward time
run recovery       env BENCH_MODE=recovery python bench.py

# compile-once layer (perf/): cold build vs warm persistent-cache build
# vs deserialized AOT executable, + the compile-level StepCostReport
run compile        env BENCH_MODE=compile python bench.py

# elastic-training drill (canonical 8-fake-device CPU mesh, re-execs
# itself there): injected pool shrink 8->4->8, mesh re-formed and the
# checkpoint resumed RESHARDED at each change; the record carries the
# goodput ledger, time-to-first-step-after-shrink, and the per-attempt
# shrink/grow classification + plan fingerprints. OBS_DIR routes the
# run's full telemetry (per-rank events, metric exports, the bench
# record itself) into one dir...
OBS_ELASTIC_DIR="$(mktemp -d /tmp/obs_elastic.XXXXXX)"
run elastic        env BENCH_MODE=elastic OBS_DIR="$OBS_ELASTIC_DIR" python bench.py

# ...which `obs report` (gke_ray_train_tpu/obs) merges into ONE
# reconciled per-run artifact: per-attempt timeline (both reshards),
# goodput ledger terms summing to attempt wall-clock exactly, the
# causal trace's per-attempt critical path (span/ledger reconciled,
# rc=3 on drift), anomaly/capture inventory, and the bench record —
# report.json stays beside the events, the summary line lands in $OUT
run obs-report     python -m gke_ray_train_tpu.obs report "$OBS_ELASTIC_DIR"

# the elastic drill's post-run self-check: `obs diff` compares the
# fresh report against the checked-in regression ledger
# (tests/regressions/elastic_cpu8.json) under two-sided tolerances —
# goodput composition, counts, serve latency, critical-path shares —
# and the verdict is its own artifact line (rc=4 prints the offending
# term delta). After an INTENTIONAL goodput change, re-record with
# REGRESSION_UPDATE=1 (or `obs diff ... --update`) and review the JSON
# diff like code.
run obs-diff       python -m gke_ray_train_tpu.obs diff "$OBS_ELASTIC_DIR" \
    tests/regressions/elastic_cpu8.json

# close the loop (ISSUE 16): fold the elastic drill's observed
# telemetry back into the autotune registry. `ingest` matches each
# bench/goodput record to a registry arm by plan fingerprint under the
# surface/chip/backend refusal gates (a cpu-fallback run can NEVER
# calibrate a TPU entry; rc=3 just means nothing matched this dir —
# not a failure on a fresh registry), then `calibrate` re-fits the
# per-chip correction factors from everything observed so far. A
# drift trip here (rc=5) marks the entry STALE — the overlay refuses
# it until re-tuned, so treat it like a failed budget check.
run autotune-ingest    python -m gke_ray_train_tpu.autotune ingest \
    "$OBS_ELASTIC_DIR" --dir tuned_plans
run autotune-calibrate python -m gke_ray_train_tpu.autotune calibrate \
    --dir tuned_plans

# compile-cost budgets (tests/budgets/*.json) are recorded on the
# canonical 8-fake-device CPU mesh, NOT on the attached chip — the CLI
# re-execs itself there; `check` is what tier-1 runs. `--all` sweeps
# EVERY checked-in preset (train + hybrid + serve) in one invocation —
# never enumerate presets by hand here. Only re-record (`record --all`)
# after an INTENTIONAL cost change, and review the JSON diff like code.
run budget-check   python -m gke_ray_train_tpu.perf.budget check --all

# shardlint (gke_ray_train_tpu/analysis): the AST pass over the repo
# plus the trace-level analyzers on the canonical CPU mesh — no
# unbudgeted reshard collectives, donation held, one compile per fn
run shardlint      python -m gke_ray_train_tpu.analysis lint
run shardlint-check python -m gke_ray_train_tpu.analysis check

# plancheck (analysis/plancheck.py): static ExecutionPlan verification
# over the shipped configs — topology feasibility, model-dim
# divisibility, the checkpoint-portability matrix, budget fingerprint
# + KNOWN_KEYS consistency. No backend needed (safe on a dead chip).
run plancheck      python -m gke_ray_train_tpu.analysis plancheck

# kernelcheck (analysis/kernelcheck.py): static kernel rules
# (KER001-006) + differential sweeps of every registered kernel vs its
# oracle against the pinned tolerance ledger (tests/tolerances/). The
# sweeps re-exec onto the canonical 8-fake-device CPU mesh (safe on a
# dead chip); only re-record the ledger (TOLERANCE_UPDATE=1) after an
# INTENTIONAL numerics change, and review the JSON diff like code.
run kernelcheck    python -m gke_ray_train_tpu.analysis kernelcheck

# flash-kernel block-size A/B (queued since r4): 3x3 sweep around the
# defaults on the seq4k shape where the kernel dominates (up to 8 extra
# bench runs; the default q=256/kv=1024 cell IS the `seq4k` record
# above and is skipped here)
for q in 128 256 512; do
  for kv in 512 1024 2048; do
    [ "$q" = 256 ] && [ "$kv" = 1024 ] && continue
    run "flash-q${q}-kv${kv}" env BENCH_MODE=seq4k \
        FLASH_BLOCK_Q="$q" FLASH_BLOCK_KV="$kv" python bench.py
  done
done

# flagship entry through its own meter (steady-state vs incl-stalls
# since r5) — full job: train + eval + ckpt + merge + export
run flagship env FINE_TUNE_CONFIG=ray-jobs/fine_tune_config_offline_8b.json \
    python ray-jobs/fine_tune_llama_ray.py

echo "records in $OUT"
