#!/usr/bin/env python
"""Regenerate the checked-in autotune calibration fixtures.

Writes three sibling fixture dirs under ``tests/fixtures/``:

- ``autotune_registry/`` — ONE registry entry for the ``tiny_fsdp8``
  preset (real base/winner plan fingerprints, real model digest, real
  CPU chip digest) whose score dicts are synthetic-but-well-formed
  roofline breakdowns. Synthetic on purpose: the fixture must stay
  byte-stable across machines, and the calibration loop only cares
  that measured/modeled pairs relate deterministically.
- ``autotune_obs/`` — an obs dir whose ``bench_records.jsonl``
  measures BOTH arms at exactly 2x the modeled step time, so
  ``autotune calibrate`` fits a compute factor of exactly 2.0 and the
  corrected prediction lands within the drift band.
- ``autotune_obs_doctored/`` — same arms measured at 10x: ingesting it
  against the fitted calibration must trip ``AUTOTUNE_DRIFT_BAND``
  (the rc=5 contract the CI smoke and tests/test_autotune.py pin).

Deterministic by construction — rerunning this script must be a
no-op diff. CI copies ``autotune_registry/`` to a scratch dir before
ingesting (ingest mutates entries in place).

Usage: JAX_PLATFORMS=cpu python scripts/make_autotune_fixture.py
"""

import dataclasses
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FIXTURES = os.path.join(REPO, "tests", "fixtures")

# synthetic roofline breakdowns (seconds). Chosen so the measured
# fixtures below fit a compute factor of EXACTLY 2.0:
#   f = sum(m*p)/sum(p^2) with m_i = 2*p_i  ->  f = 2
BASE_SCORE = {
    "chip": "cpu",
    "t_compute_s": 0.02,
    "t_hbm_s": 0.01,
    "t_ici_s": 0.003,
    "t_dcn_s": 0.0,
    "exposed_penalty_s": 0.003,
    "binding": "compute",
    "mfu_ceiling": 0.5,
    "modeled_step_s": 0.023,
}
WINNER_SCORE = {
    "chip": "cpu",
    "t_compute_s": 0.016,
    "t_hbm_s": 0.01,
    "t_ici_s": 0.003,
    "t_dcn_s": 0.0,
    "exposed_penalty_s": 0.003,
    "binding": "compute",
    "mfu_ceiling": 0.5,
    "modeled_step_s": 0.019,
}
MEASURED_FACTOR_GOOD = 2.0       # within AUTOTUNE_DRIFT_BAND once fitted
MEASURED_FACTOR_DOCTORED = 10.0  # trips the band against that same fit


def build_entry(directory: str) -> dict:
    from gke_ray_train_tpu.autotune.registry import save_entry
    from gke_ray_train_tpu.autotune.space import TUNABLE_FIELDS
    from gke_ray_train_tpu.perf.budget import (
        plan_for_preset, preset_model_cfg)

    base = plan_for_preset("tiny_fsdp8")
    cfg = preset_model_cfg("tiny_fsdp8")
    winner = dataclasses.replace(base, fused_ops=True)
    base_row = {"fingerprint": base.fingerprint(),
                "plan_fingerprint": base.fingerprint(),
                "score": dict(BASE_SCORE), "diff": {}, "env": None,
                "distance": 0}
    winner_row = {"fingerprint": winner.fingerprint(),
                  "plan_fingerprint": winner.fingerprint(),
                  "score": dict(WINNER_SCORE),
                  "diff": {"fused_ops": [False, True]}, "env": None,
                  "distance": 1}
    result = {
        "surface": "train",
        "chip": "cpu",
        "scorer_version": 1,
        "base": base_row,
        "winner": winner_row,
        "winner_tuned_fields": {f: getattr(winner, f)
                                for f in TUNABLE_FIELDS["train"]},
        "winner_env": {},
        "improvement": round(BASE_SCORE["modeled_step_s"]
                             / WINNER_SCORE["modeled_step_s"], 6),
        "candidates": [winner_row, base_row],
        "space": {"enumerated": 2, "statically_pruned": 0,
                  "coarse_skipped": 0, "compiled": 2, "scored": 2,
                  "dims": ["fused"]},
        "pruned": [],
    }
    path = save_entry(result, base_plan=base, model_cfg=cfg,
                      directory=directory)
    # the jax version stamp is provenance on real entries but noise in
    # a checked-in fixture — pin it so regeneration is byte-stable
    with open(path) as f:
        doc = json.load(f)
    doc["_recorded_with"] = {"jax": "fixture"}
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return {"base_fp": base.fingerprint(),
            "winner_fp": winner.fingerprint(), "path": path}


def write_obs_dir(directory: str, fps: dict, factor: float,
                  run_id: str) -> None:
    os.makedirs(directory, exist_ok=True)
    rec = {
        "metric": "autotune default-vs-tuned fixture record",
        "value": 1.0,
        "unit": "x",
        "run_id": run_id,
        "backend": "cpu",
        "topology": "cpu-8",
        "steps": 5,
        "plan_fingerprint_default": fps["base_fp"],
        "plan_fingerprint_tuned": fps["winner_fp"],
        "measured_step_s_default": round(
            factor * BASE_SCORE["modeled_step_s"], 6),
        "measured_step_s_tuned": round(
            factor * WINNER_SCORE["modeled_step_s"], 6),
    }
    with open(os.path.join(directory, "bench_records.jsonl"), "w") as f:
        f.write(json.dumps(rec, sort_keys=True) + "\n")


def main() -> None:
    reg_dir = os.path.join(FIXTURES, "autotune_registry")
    os.makedirs(reg_dir, exist_ok=True)
    fps = build_entry(reg_dir)
    write_obs_dir(os.path.join(FIXTURES, "autotune_obs"), fps,
                  MEASURED_FACTOR_GOOD, "fixture-good")
    write_obs_dir(os.path.join(FIXTURES, "autotune_obs_doctored"), fps,
                  MEASURED_FACTOR_DOCTORED, "fixture-doctored")
    print(f"fixtures written under {FIXTURES}")
    print(f"  entry: {fps['path']}")
    print(f"  base {fps['base_fp']} winner {fps['winner_fp']}")


if __name__ == "__main__":
    main()
