"""Dataset-prep job (SURVEY.md §3.3).

Capability parity with
/root/reference/ray-jobs/prepare_wikitext2_ray_job.py: a 1-CPU Ray task
downloads wikitext-2-raw-v1 and writes concatenated raw text per split to
shared storage, idempotently; the driver submits and waits with a 30-min
timeout. Runs locally (no Ray) with the same code path.
"""

from __future__ import annotations

import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

logging.basicConfig(level=logging.INFO)
logger = logging.getLogger("prepare_wikitext2")

OUTPUT_DIR = os.environ.get("DATA_DIR", "/mnt/pvc/data")


def prepare_task(output_dir: str) -> dict:
    from gke_ray_train_tpu.data import prepare_wikitext2
    return prepare_wikitext2(
        output_dir,
        synthetic_fallback=os.environ.get("SYNTHETIC_FALLBACK", "0") == "1")


if __name__ == "__main__":
    try:
        import ray
        ray.init(address=os.environ.get("RAY_ADDRESS", "auto"))
        task = ray.remote(num_cpus=1)(prepare_task)
        ref = task.remote(OUTPUT_DIR)
        paths = ray.get(ref, timeout=1800)  # reference: 30-min timeout
    except (ImportError, ConnectionError) as e:
        logger.info("no Ray cluster (%s); running locally", type(e).__name__)
        paths = prepare_task(OUTPUT_DIR)
    for split, p in paths.items():
        logger.info("%s: %s (%d bytes)", split, p, os.path.getsize(p))
