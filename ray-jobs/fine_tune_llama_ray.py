"""LLM fine-tune entry point — TPU-native flagship job.

Capability parity with the reference fine-tune
(/root/reference/ray-jobs/fine_tune_llama_ray.py): submitted via
``ray job submit -- python ray-jobs/fine_tune_llama_ray.py``, reads
``ray-jobs/fine_tune_config.json`` (same UPPER_CASE keys + mesh keys,
SURVEY.md §5.6), runs a per-worker train fn on every TPU host, saves
merged/full weights in HF layout to shared storage, optionally runs the
base-vs-tuned inference comparison (§3.4).

What replaces what (SURVEY.md §2b):
- TorchTrainer/ScalingConfig        → rayint.JaxTrainer / ScalingConfig
- Accelerate + NCCL process group    → jax.distributed + GSPMD mesh
- BitsAndBytes NF4 QLoRA             → LoRA adapter pytree over an
  NF4/int8-quantized frozen base (ops/quant.py; QUANT_KIND config key)
- TRL SFTTrainer                     → jitted train step + host loop
- HF Trainer checkpoints             → orbax manager w/ retention + resume
"""

from __future__ import annotations

import json
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

logging.basicConfig(level=logging.INFO,
                    format="%(asctime)s %(name)s: %(message)s")
logger = logging.getLogger("fine_tune")


def train_loop_per_worker(config: dict):
    """Runs on every TPU host (same shape as the reference's worker fn,
    fine_tune_llama_ray.py:198)."""
    import jax
    import numpy as np

    from gke_ray_train_tpu.ckpt import (
        CheckpointManager, load_hf_checkpoint, save_hf_checkpoint)
    from gke_ray_train_tpu.data import (
        ByteTokenizer, downsample, load_hf_tokenizer, pad_sft_rows,
        pack_examples, sft_epoch_batches, synthetic_sql_rows,
        tokenize_sft_example, format_gretel_sql_example)
    from gke_ray_train_tpu.models import (
        init_params, param_specs, preset_for_model_id, tiny)
    from gke_ray_train_tpu.parallel.mesh import distributed_init
    from gke_ray_train_tpu.parallel.placement import (
        host_batch_size, input_shard_layout, make_place_batch)
    from gke_ray_train_tpu.parallel.sharding import tree_shardings
    from gke_ray_train_tpu.rayint import get_context
    from gke_ray_train_tpu.train import (
        LoraConfig, ThroughputMeter, make_train_state, make_train_step,
        make_eval_step, merge_lora)
    from gke_ray_train_tpu.train.loop import run_training
    from gke_ray_train_tpu.train.profiling import (
        apply_debug_flags, profiler_from_config)
    from gke_ray_train_tpu.train.tb import writer_from_config

    from gke_ray_train_tpu.config import (
        audit_config, cadence_from_config, optimizer_from_config,
        quant_kind_from_config, schedule_from_config)

    ctx = get_context()
    if ctx.is_host0():
        audit_config(config)   # §5.6: every key honored or warned, never
                               # silently dropped
    # ONE declarative ExecutionPlan (plan.py) resolves every execution
    # knob — mesh, batch shape, donation, prefetch, compile-once
    # policy, runtime guards — from the config (env fallback), and its
    # fingerprint identifies the run in cache dirs, AOT sidecar keys
    # and BENCH/budget records
    from gke_ray_train_tpu.plan import ExecutionPlan, compile_step_with_plan
    plan = ExecutionPlan.resolve(config)
    apply_debug_flags(config)
    distributed_init()
    # elastic mesh re-formation (rayint/elastic.py): a shrunken/grown
    # pool re-resolves the plan on the survivors (data/fsdp reflowed,
    # global batch preserved, budget pin dropped) and the mesh is built
    # on exactly those devices; the checkpoint restore below reshards
    # from the logical spec. A no-op when ELASTIC is off. Replan BEFORE
    # enabling the cache — the cache subdir is namespaced by the plan's
    # compile fingerprint, which must be the survivors'.
    from gke_ray_train_tpu.rayint.elastic import maybe_replan
    plan, devices = maybe_replan(plan, config=config, log=logger)
    # tuned-plan overlay (autotune/registry.py): with AUTOTUNE=1,
    # overlay the registry hit keyed by (model digest, topology,
    # surface) onto the resolved plan — AFTER the replan so a reshard
    # re-keys the lookup, BEFORE the cache/mesh so everything compiles
    # the tuned program. Loud apply, loud refusal on drift.
    from gke_ray_train_tpu.autotune.registry import maybe_apply
    plan, _ = maybe_apply(plan, config=config, log=logger)
    # persistent XLA compile cache (perf/cache.py): restarts and peer
    # hosts reuse the compiled binary; re-enabled post-init so the
    # cache dir carries the real device-topology fingerprint
    from gke_ray_train_tpu.perf.cache import enable_persistent_cache
    enable_persistent_cache(plan=plan)
    mesh = plan.build_mesh(devices)
    n_hosts = max(jax.process_count(), 1)
    host = jax.process_index()
    smoke = bool(config.get("SMOKE_TEST", False))
    logger.info("worker %d/%d; %d devices; mesh %s; plan %s", host,
                n_hosts, len(devices), dict(mesh.shape),
                plan.fingerprint())

    # ---- tokenizer + model config ------------------------------------
    model_id = config["MODEL_ID"]
    hf_token = os.environ.get("HF_TOKEN")
    try:
        tokenizer = load_hf_tokenizer(model_id, hf_token)
    except Exception as e:
        logger.warning("HF tokenizer unavailable (%s); using ByteTokenizer",
                       type(e).__name__)
        tokenizer = ByteTokenizer()

    max_seq = plan.max_seq_len
    use_lora = bool(config.get("USE_QLORA", False))
    # frozen-base (Q)LoRA keeps unquantized leaves (embed/lm_head/norms)
    # in the compute dtype — fp32 embeddings alone add ~4 GB at 8B dims
    # and the base takes no optimizer update; full FT defaults to fp32
    # master params (reference: bf16 base via BNB_4BIT_COMPUTE_DTYPE)
    train_dtype = config.get("TRAIN_DTYPE", "bfloat16")
    param_dtype = config.get("PARAM_DTYPE",
                             train_dtype if use_lora else "float32")
    if smoke:
        # smoke keeps its fp32-by-default dtypes (CPU numerics), but an
        # explicit PARAM_DTYPE rehearses the flagship memory behavior
        # size the smoke model's depth to the pipeline: layers must
        # divide by pipe stages x virtual groups or the forward raises
        pipe_depth = (int(mesh.shape.get("pipe", 1))
                      * int(config.get("PIPE_VIRTUAL_STAGES", 1)))
        cfg = tiny(vocab_size=max(getattr(tokenizer, "vocab_size", 260), 260),
                   max_seq_len=max_seq, dtype=config.get("TRAIN_DTYPE",
                                                         "float32"),
                   param_dtype=config.get("PARAM_DTYPE", "float32"),
                   attn_impl=config.get("ATTN_IMPL", "auto"),
                   n_layers=max(2, pipe_depth))
    else:
        cfg = preset_for_model_id(
            model_id,
            dtype=train_dtype,
            param_dtype=param_dtype,
            attn_impl=config.get("ATTN_IMPL", "auto"),
            remat_policy=config.get("REMAT_POLICY", "full"))

    # ---- weights ------------------------------------------------------
    # resolution order (reference: from_pretrained(MODEL_ID),
    # fine_tune_llama_ray.py:240): explicit local dir → hub snapshot →
    # random init (smoke/offline only, with a loud warning). Every
    # branch decision is COLLECTIVE — hosts disagreeing on which branch
    # to take would deadlock in the first collective or train garbage.
    ckpt_dir = config.get("PRETRAINED_CHECKPOINT_DIR")
    have_local = bool(ckpt_dir and os.path.exists(str(ckpt_dir)))
    if n_hosts > 1:
        from jax.experimental import multihost_utils
        have_local = bool(int(multihost_utils.broadcast_one_to_all(
            np.asarray(1 if have_local else 0, np.int32))))
        if have_local and not (ckpt_dir and os.path.exists(str(ckpt_dir))):
            raise FileNotFoundError(
                f"host 0 sees PRETRAINED_CHECKPOINT_DIR={ckpt_dir} but "
                f"host {host} does not — put it on shared storage "
                "(/mnt/pvc)")
    if not have_local and not smoke:
        from gke_ray_train_tpu.ckpt.hub import acquire_pretrained
        # cache location comes from HF_HOME (the RayCluster CR mounts
        # /mnt/hf_cache there), read by huggingface_hub itself.
        # acquire_pretrained's fallback decision is itself collective.
        ckpt_dir = acquire_pretrained(model_id, token=hf_token,
                                      num_hosts=n_hosts, host_id=host)
        have_local = ckpt_dir is not None
    quant_kind = quant_kind_from_config(config, use_lora)
    load_quant = quant_kind if (use_lora and quant_kind != "none") else None
    already_quantized = False
    if have_local:
        # QLoRA bases quantize DURING the stream (one layer-slice on
        # device at a time) — 8B fits a single 16 GB chip this way, the
        # same shape as the reference's BitsAndBytesConfig load
        params = load_hf_checkpoint(str(ckpt_dir), cfg, mesh=mesh,
                                    quantize=load_quant)
        already_quantized = load_quant is not None
        logger.info("loaded pretrained weights from %s%s", ckpt_dir,
                    f" (quantized {load_quant} on load)" if load_quant
                    else "")
    else:
        if not smoke:
            logger.warning(
                "no local checkpoint and hub unreachable; initializing "
                "RANDOM weights (fine-tuning semantics require a "
                "pretrained checkpoint)")
        if load_quant is not None:
            # QLoRA random init quantizes DURING init (one repeat-slice
            # at a time, models/qinit.py) — full-dim 8B never
            # materializes fp32, so offline flagship-dims runs fit one
            # 16 GB chip just like the stream-load path
            from gke_ray_train_tpu.models.qinit import init_quantized_params
            params = init_quantized_params(cfg, jax.random.key(0),
                                           kind=load_quant, mesh=mesh)
            already_quantized = True
        else:
            p_shard = tree_shardings(mesh, param_specs(cfg))
            params = jax.jit(lambda k: init_params(cfg, k),
                             out_shardings=p_shard)(jax.random.key(0))

    # ---- dataset ------------------------------------------------------
    n_train = int(config.get("NUM_TRAIN_SAMPLES", 1000))
    n_eval = int(config.get("NUM_EVAL_SAMPLES", 200))
    try:
        from datasets import load_dataset
        ds_train = list(load_dataset(config["DATASET_NAME"], split="train"))
        ds_test = list(load_dataset(config["DATASET_NAME"], split="test"))
    except Exception as e:
        logger.warning("dataset hub unavailable (%s); synthetic SQL rows",
                       type(e).__name__)
        ds_train = synthetic_sql_rows(max(n_train, 64), seed=0)
        ds_test = synthetic_sql_rows(max(n_eval, 16), seed=1)
    # downsample-with-seed parity (reference :288-289)
    ds_train = downsample(ds_train, n_train)
    ds_test = downsample(ds_test, n_eval)

    def tokenize_rows(rows):
        return [tokenize_sft_example(
            tokenizer, format_gretel_sql_example(r), max_len=max_seq + 1)
            for r in rows]

    train_exs = tokenize_rows(ds_train)
    eval_exs = tokenize_rows(ds_test)
    n_dead = sum(1 for ex in train_exs if ex["loss_weights"].sum() == 0)
    if n_dead:
        logger.warning(
            "%d/%d train examples have ZERO trainable tokens — the prompt "
            "fills MAX_SEQ_LENGTH=%d and truncation drops the completion; "
            "raise MAX_SEQ_LENGTH or shorten prompts", n_dead,
            len(train_exs), max_seq)
    if n_dead == len(train_exs):
        raise ValueError("every train example truncated to zero trainable "
                         "tokens; training would silently learn nothing")

    per_device_batch = plan.per_device_batch
    grad_accum = plan.grad_accum
    data_par = mesh.shape["data"] * mesh.shape["fsdp"]
    global_batch = per_device_batch * data_par * grad_accum
    # input partitioning follows the mesh, not process_count: hosts
    # spanned by model/context axes feed identical rows (placement.py)
    in_shards, in_shard_id = input_shard_layout(mesh)
    host_batch = host_batch_size(global_batch, num_shards=in_shards)

    packing = plan.packing
    if packing:
        packed = list(pack_examples(train_exs, max_seq))
        train_rows = {k: np.stack([r[k] for r in packed])
                      for k in packed[0]}
    else:
        train_rows = pad_sft_rows(train_exs, max_seq)
    eval_rows = pad_sft_rows(eval_exs, max_seq)

    # ceil: the final partial batch trains too (sft_epoch_batches keeps
    # the tail as a zero-weight-padded batch, HF drop_last=False parity)
    steps_per_epoch = max(
        -(-len(train_rows["inputs"]) // global_batch), 1)
    epochs = int(config.get("NUM_TRAIN_EPOCHS", 1))
    total_steps = steps_per_epoch * epochs

    # ---- optimizer / adapters ----------------------------------------
    lora_cfg = LoraConfig.from_dict(config) if use_lora else None
    # OPTIM / LR_SCHEDULER_TYPE honored (config.py; reference
    # fine_tune_config.json:15-17)
    schedule = schedule_from_config(config, total_steps)
    opt = optimizer_from_config(config, schedule)
    # QLoRA = LoRA adapters over a *quantized* frozen base (the
    # reference's BitsAndBytesConfig 4-bit NF4 load,
    # fine_tune_llama_ray.py:216-227) — here a pytree transform
    # (ops/quant.py), dequantized inside the jitted forward.
    if use_lora and quant_kind != "none" and not already_quantized:
        from gke_ray_train_tpu.ops.quant import quantize_params
        params = quantize_params(params, kind=quant_kind)
        logger.info("quantized frozen base weights to %s", quant_kind)
    # hand the acquired weights in — make_train_state must NOT random-init
    # its own full fp32 tree first (at 8B dims that alone OOMs one chip)
    state = make_train_state(cfg, opt, jax.random.key(1), mesh=mesh,
                             lora_cfg=lora_cfg, params=params)

    # pipeline-parallel meshes (MESH_PIPE>1) microbatch each forward;
    # 0/unset = default (one microbatch per stage) — all plan-resolved
    pipe_micro = plan.pipe_microbatches or None
    if "PIPE_VIRTUAL_STAGES" in config:
        import dataclasses as _dc
        # invalid values (0, negatives) must fail ModelConfig validation,
        # not silently fall back to the shift schedule
        cfg = _dc.replace(cfg, pipe_virtual=plan.pipe_virtual_stages)
    # grad_accum / donation / pipe microbatching come from the plan —
    # make_train_step routes through the one compile surface
    # (plan.compile_step_with_plan)
    step_fn = make_train_step(cfg, opt, mesh=mesh, lora_cfg=lora_cfg,
                              schedule=schedule, plan=plan)
    # explicit batch shardings pin eval to ONE compiled layout (no
    # retrace per distinct batch placement, no silent replication on
    # multi-host meshes) — the same contract the train step gets from
    # make_place_batch
    from gke_ray_train_tpu.train.step import batch_shardings
    # ground truth from the BUILT mesh (a declared -1 context axis may
    # have filled to >1; plan.context_sharded resolves, but the mesh is
    # authoritative at this point)
    ctx_sharded = mesh.shape["context"] > 1
    eval_fn_step = make_eval_step(
        cfg, mesh=mesh, lora_cfg=lora_cfg, pipe_microbatches=pipe_micro,
        batch_shardings=batch_shardings(
            mesh, ("inputs", "targets", "weights"),
            context_sharded=ctx_sharded))
    out_base = config.get("OUTPUT_DIR_BASE", "/tmp/grt_sft")
    sft_dir = os.path.join(out_base, config.get("SFT_SUBDIR_NAME", "sft"))
    # AOT train executable beside the checkpoint (perf/cache.py), under
    # the plan's policy: a preempted retry deserializes it and reaches
    # its first step with zero retracing; signature OR plan-fingerprint
    # drift falls back to the jitted step
    from gke_ray_train_tpu.perf.cache import make_abstract_batch
    step_fn = compile_step_with_plan(
        plan, mesh, step_fn, state,
        make_abstract_batch(mesh, global_batch, max_seq,
                            packed=packing,
                            context_sharded=ctx_sharded),
        sidecar=os.path.join(sft_dir, "aot_train_step.bin"),
        label="sft train_step")
    # SAVE_STRATEGY / EVALUATION_STRATEGY_SFT honored (config.py;
    # reference fine_tune_config.json:22-25)
    cadence = cadence_from_config(config)
    mgr = None
    if cadence["save_enabled"]:
        # recency retention, keep 2: the SFT manager exists to RESUME
        # (the final model is exported separately below) — best-by-loss
        # retention would garbage-collect a grace-window preemption
        # save whose loss is not among the best, and the
        # corrupt-checkpoint fallback (ckpt/manager.py) needs an
        # earlier restorable step to survive an interrupted latest save
        # goodput knobs (ASYNC_CKPT / PEER_REPLICATION /
        # CKPT_COMMIT_TIMEOUT_S): same dual-read + semantics as the
        # pretrain entry point — the RESUME manager commits async and
        # replicates to the peer slice; the export manager below stays
        # synchronous (a final artifact has no goodput to protect)
        def _goodput_flag(key):
            return str(config.get(key, os.environ.get(key, "0"))
                       ).strip().lower() not in ("", "0", "false", "no")
        peer = None
        if _goodput_flag("PEER_REPLICATION"):
            from gke_ray_train_tpu.ckpt.peer import PeerReplicator
            peer = PeerReplicator.from_env()
        mgr = CheckpointManager(
            sft_dir, max_to_keep=2, score_attribute=None,
            async_commit=_goodput_flag("ASYNC_CKPT"),
            commit_timeout_s=float(config.get(
                "CKPT_COMMIT_TIMEOUT_S",
                os.environ.get("CKPT_COMMIT_TIMEOUT_S", "120"))),
            peer=peer)

    group_by_length = bool(config.get("GROUP_BY_LENGTH", False))
    if group_by_length and packing:
        logger.warning("GROUP_BY_LENGTH is redundant under PACKING; "
                       "packed sequences have no padding to group away")
        group_by_length = False

    def epoch_batches(epoch):
        yield from sft_epoch_batches(
            train_rows, global_batch, num_hosts=in_shards,
            host_id=in_shard_id, epoch=epoch,
            group_by_length=group_by_length)

    def eval_fn(st):
        # eval rows are PARTITIONED across input-shard groups (the
        # reference gets the same from HF Trainer's DistributedSampler
        # eval): each group walks 1/in_shards of the rows, the jitted
        # step reduces over the global placed batch, zero-weight padding
        # keeps every shard in lockstep — exact eval loss at 1/in_shards
        # the per-host work (train/evaluate.py)
        from gke_ray_train_tpu.train.evaluate import sharded_eval_loss
        return {"eval_loss": sharded_eval_loss(
            st, eval_fn_step, eval_rows, host_batch=host_batch,
            in_shards=in_shards, in_shard_id=in_shard_id,
            place_batch=place)}

    # LoRA runs bill the 4N FLOP count (frozen base skips weight-grad
    # matmuls) so the logged MFU is honest (train/metrics.py)
    meter = ThroughputMeter(cfg, seq_len=max_seq,
                            n_devices=len(devices),
                            trainable="lora" if use_lora else "full")
    # LoRA checkpoints persist only adapters + optimizer state: the
    # frozen (possibly NF4-quantized) base is rebuilt from the pretrained
    # weights on resume — smaller checkpoints, and sub-byte code arrays
    # never hit the serializer.
    ckpt_view = None
    if use_lora:
        ckpt_view = (
            lambda st: st._replace(params={}),
            lambda st, v: v._replace(params=st.params),
        )
    # multi-host batch form-up (SURVEY.md row D9): host-local rows →
    # global sharded arrays; identical path single-host
    place = make_place_batch(mesh, context_sharded=ctx_sharded)

    # shardlint runtime guards (analysis/guards.py), resolved from the
    # plan (config-key-first, env fallback — same precedence as before)
    state, metrics = run_training(
        state, step_fn, epoch_batches,
        epochs=epochs,
        place_batch=place,
        guards=plan.runtime_guards(),
        # asynchronous input pipeline (data/prefetch.py): tokenize/pack +
        # sharded host→device transfer overlap the train step; depth 2
        # device-resident batches by default, 0 = synchronous
        prefetch=plan.prefetch,
        log_every=int(config.get("LOGGING_STEPS", 10)),
        meter=meter, ckpt_manager=mgr,
        report_fn=lambda m: ctx.report(m),
        eval_fn=eval_fn if cadence["eval_enabled"] else None,
        eval_every=cadence["eval_every"],
        eval_at_epoch_end=cadence["eval_at_epoch_end"],
        ckpt_every=cadence["ckpt_every"],
        ckpt_view=ckpt_view,
        # step-granular liveness reports for the heartbeat supervisor
        # (rayint/supervisor.py); a no-op when no sink is wired
        heartbeat_fn=ctx.heartbeat,
        profiler=profiler_from_config(
            config, os.path.join(out_base, "profile")),
        # REPORT_TO honored (reference fine_tune_config.json:26):
        # host-0 TB scalars incl. tokens/sec/chip + MFU
        tb_writer=writer_from_config(
            config, os.path.join(out_base, "tensorboard"),
            is_host0=ctx.is_host0()),
        is_host0=ctx.is_host0())

    # ---- save final artifacts (HF layout, §5.4) ----------------------
    if use_lora:
        final_dir = os.path.join(
            out_base, config.get("MERGED_MODEL_SUBDIR_NAME", "merged"))
    else:
        merged = state.params
        final_dir = os.path.join(
            out_base, config.get("FULL_FT_MODEL_SUBDIR_NAME", "full"))
    if ctx.is_host0() and n_hosts == 1:
        if use_lora:
            # merge on the HOST: dequantizing an 8B NF4 base into a
            # merged fp32 tree (~32 GB) OOMs a single 16 GB chip, and
            # single-host means no other chip holds the rest
            merged = merge_lora(state.params, state.lora, lora_cfg,
                                on_host=True)
        save_hf_checkpoint(merged, cfg, final_dir)
        # tokenizer beside the weights — the output dir must be a
        # self-contained artifact the user can hand straight to
        # AutoTokenizer/from_pretrained, matching the reference
        # (fine_tune_llama_ray.py:355,374)
        from gke_ray_train_tpu.data import save_tokenizer
        save_tokenizer(tokenizer, final_dir)
        logger.info("saved final model + tokenizer to %s", final_dir)
        # obs: exports are run events too — `obs report` shows what
        # artifacts the run produced and when (no-op when obs is off)
        from gke_ray_train_tpu.obs import runtime as obs_runtime
        obs_runtime.emit("export", path=final_dir,
                         what="merged" if use_lora else "full")
    elif n_hosts > 1:
        if use_lora:
            # sharded across hosts: each device holds 1/N of the
            # dequantized tree — the on-device merge fits by design
            merged = merge_lora(state.params, state.lora, lora_cfg)
        # multi-host export path: orbax save (collective) + model-config
        # sidecar, then `python -m gke_ray_train_tpu.ckpt.convert
        # <dir>_orbax <dir>` offline (ckpt/convert.py). Block leaves are
        # saved per-layer (unstack_for_export) so the converter can
        # restore O(one layer) at a time at 70B scale.
        from gke_ray_train_tpu.ckpt.convert import (
            unstack_for_export, write_sidecar)
        # explicitly synchronous even under ASYNC_CKPT=1: a final
        # export has no goodput to protect, and the save must be
        # durable before write_sidecar runs
        export_mgr = CheckpointManager(final_dir + "_orbax", max_to_keep=1,
                                       score_attribute=None,
                                       async_commit=False, peer=False)
        export_mgr.save(int(jax.device_get(state.step)),
                        unstack_for_export(merged), force=True)
        export_mgr.wait()
        if ctx.is_host0():
            write_sidecar(cfg, final_dir + "_orbax")
            # tokenizer rides in a subdir of the orbax export; the
            # offline converter copies it into the final HF dir so the
            # multi-host artifact is self-contained too
            from gke_ray_train_tpu.data import save_tokenizer
            save_tokenizer(tokenizer,
                           os.path.join(final_dir + "_orbax", "tokenizer"))
    if use_lora:
        # LoRA-mode inference below uses base + adapters, never the
        # merged tree — release it (the 8B host merge holds ~32 GB)
        merged = None

    # ---- optional inference comparison (§3.4) ------------------------
    # COLLECTIVE: every host enters the comparison — the params are
    # mesh-sharded global arrays, so a host-0-only generate would
    # diverge the SPMD program (the reference's rank-0 gate at :381-395
    # is only valid because DDP replicates weights). is_host0 gates
    # printing and the JSON write inside run_inference_comparison; every
    # host holds identical ds_test rows (seeded downsample/synthetic).
    if bool(config.get("INFERENCE", False)):
        from gke_ray_train_tpu.inference import run_inference_comparison
        # NOTE: the pre-training `params` handle was donated into the train
        # step (buffer aliasing), so it must not be used here. In LoRA mode
        # the base weights sit unchanged in state.params; in full-FT mode
        # reload them (the reference reloads from the hub, :69-76).
        # `have_local` (not a fresh os.path.exists) keeps the branch
        # choice collective — it was agreed across hosts at load time.
        if use_lora:
            # tuned = frozen base + adapters applied at decode time — a
            # merged copy of a quantized 8B base would not fit on-device
            base_params = tuned_params = state.params
        elif have_local:
            base_params = load_hf_checkpoint(str(ckpt_dir), cfg, mesh=mesh)
            tuned_params = merged
        else:
            if ctx.is_host0():
                logger.warning(
                    "full-FT smoke without a pretrained checkpoint: "
                    "comparing tuned model against itself")
            base_params = tuned_params = merged
        run_inference_comparison(
            base_params, tuned_params, cfg, tokenizer, ds_test,
            num_samples=int(config.get("NUM_EVAL_SAMPLES_INFERENCE", 2)),
            max_new_tokens=int(
                config.get("MAX_NEW_GENERATION_TOKENS_INFERENCE", 300)),
            output_path=os.path.join(out_base, "inference_comparison.json"),
            row_filter=(lambda r: r.get("sql_complexity")
                        == "window functions"),
            mesh=mesh, is_host0=ctx.is_host0(),
            tuned_lora=state.lora if use_lora else None,
            lora_scale=lora_cfg.scale if use_lora else 1.0)

    # ---- optional post-train serving smoke (serve/, ROADMAP #2) ------
    # train → serve in the same process: the comparison prompts run
    # through the continuous-batching engine on the just-trained
    # weights (LoRA runs serve base + adapters, never a merged tree).
    # Single-host only: the engine's host-side scheduler is per-replica
    # by design — a multi-host job serves via rayint/serving.py
    # replicas instead.
    # config-then-env (the README's "config and/or env" contract),
    # str-parsed like SMOKE_TEST: the documented disable value "0"
    # must actually disable (bool("0") is True)
    serve_flag = config.get("SERVE_AFTER_TRAIN",
                            os.environ.get("SERVE_AFTER_TRAIN", "0"))
    if str(serve_flag).strip().lower() in ("1", "true"):
        if n_hosts > 1:
            logger.warning(
                "SERVE_AFTER_TRAIN is single-host only (deploy "
                "rayint/serving.py replicas for multi-host serving); "
                "skipping")
        else:
            import numpy as np

            from gke_ray_train_tpu.data.sft import render_chat
            from gke_ray_train_tpu.serve import post_train_smoke
            eos = ([int(tokenizer.eos_token_id)]
                   if getattr(tokenizer, "eos_token_id", None) is not None
                   else [])
            prompts = []
            for row in ds_test[:int(
                    config.get("NUM_EVAL_SAMPLES_INFERENCE", 2))]:
                msgs = format_gretel_sql_example(row)
                text = render_chat(tokenizer, msgs,
                                   add_generation_prompt=True)
                prompts.append(np.asarray(
                    tokenizer(text, add_special_tokens=False)["input_ids"],
                    np.int32))
            out = post_train_smoke(
                state.params, cfg, plan, prompts, eos_ids=eos,
                lora=state.lora if use_lora else None,
                lora_scale=lora_cfg.scale if use_lora else 1.0,
                # LoRA runs tag every smoke request with the trained
                # adapter's id, so the smoke exercises the multi-tenant
                # batched-adapter decode path end to end (ISSUE 17) —
                # serve_smoke.json then records the adapter counters
                adapter_ids=(["tuned"] * len(prompts) if use_lora
                             else None),
                max_new_tokens=64)
            if out is not None and ctx.is_host0():
                comps, stats = out
                for c in comps:
                    logger.info("serve smoke %s (%s): %s", c.rid,
                                c.finish_reason,
                                tokenizer.decode(c.generated))
                # out_base may not exist yet (SAVE_STRATEGY=no and no
                # AOT sidecar = nothing else created it); a smoke must
                # not kill a finished training run
                os.makedirs(out_base, exist_ok=True)
                with open(os.path.join(out_base, "serve_smoke.json"),
                          "w") as f:
                    json.dump(stats, f, indent=2)
                # serving latency/occupancy -> TB, via the SAME obs
                # registry the engine exported into (train/tb.py
                # log_registry; the loop's writer is closed by now, so
                # a short-lived one publishes the post-train scalars)
                from gke_ray_train_tpu.obs import runtime as obs_runtime
                if obs_runtime.registry() is not None:
                    w = writer_from_config(
                        config, os.path.join(out_base, "tensorboard"),
                        is_host0=True)
                    if w is not None:
                        w.log_registry(int(jax.device_get(state.step)),
                                       obs_runtime.registry())
                        w.close()
    return metrics


if __name__ == "__main__":
    from gke_ray_train_tpu.rayint import JaxTrainer, RunConfig, ScalingConfig
    from gke_ray_train_tpu.rayint.trainer import FailureConfig

    cfg_path = os.environ.get(
        "FINE_TUNE_CONFIG",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "fine_tune_config.json"))
    try:
        with open(cfg_path) as f:
            config = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        logger.error("failed to load %s: %s", cfg_path, e)
        sys.exit(1)

    scaling = ScalingConfig.from_env()
    trainer = JaxTrainer(
        train_loop_per_worker,
        train_loop_config=config,
        scaling_config=scaling,
        run_config=RunConfig(
            name="llama-sft-tpu",
            storage_path=config.get("OUTPUT_DIR_BASE"),
            # fault-tolerance knobs (see README "Fault tolerance" and
            # ray-jobs/README.md): genuine failures retry with backoff
            # against MAX_FAILURES; spot preemptions (SIGTERM →
            # checkpoint within PREEMPT_GRACE_S) are budgeted separately
            failure_config=FailureConfig(
                max_failures=int(os.environ.get("MAX_FAILURES", "0")),
                max_preemptions=int(
                    os.environ.get("MAX_PREEMPTIONS", "8"))),
            # hang detection (rayint/trainer.py): unset = wait forever
            worker_timeout_s=(float(os.environ["WORKER_TIMEOUT_S"])
                              if "WORKER_TIMEOUT_S" in os.environ
                              else None),
            # step-granular supervision (rayint/supervisor.py): kill an
            # attempt — naming the stalled rank — when a worker makes no
            # step progress for this long; unset = no heartbeat watch
            heartbeat_timeout_s=(float(os.environ["HEARTBEAT_TIMEOUT_S"])
                                 if "HEARTBEAT_TIMEOUT_S" in os.environ
                                 else None)),
    )
    result = trainer.fit()
    if result.error:
        logger.error("training %s after %d attempt(s) "
                     "(%d preemption(s)): %s", result.status,
                     result.attempts, result.preemptions, result.error)
        sys.exit(1)
    logger.info("final metrics: %s (attempts=%d preemptions=%d)",
                result.metrics, result.attempts, result.preemptions)
    # unified telemetry (obs/): point the operator at the one merged
    # per-run view of what just happened
    from gke_ray_train_tpu.obs.runtime import resolve_obs_dir
    _obs_dir = resolve_obs_dir(None, config)
    if _obs_dir is not None:
        logger.info("run telemetry: python -m gke_ray_train_tpu.obs "
                    "report %s --text", _obs_dir)
    # one machine-readable line on stdout (logging goes to stderr) so
    # drivers/scripts (scripts/record_baselines.sh) can collect the
    # job's meter numbers the same way they collect bench.py records
    print(json.dumps({"metric": "flagship_final",
                      "attempts": result.attempts,
                      "preemptions": result.preemptions, **{
                          k: v for k, v in (result.metrics or {}).items()
                          if isinstance(v, (int, float))}}), flush=True)
