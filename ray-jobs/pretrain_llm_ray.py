"""From-scratch LLM pre-train entry point — the visible, hackable loop.

Capability parity with the reference pre-train
(/root/reference/ray-jobs/pytorch_llm_ray.py): char-tokenize wikitext-2,
train a ~1.2B decoder-only transformer (2048d/24L/16H/8192ff) with
warmup-cosine AdamW, grad clip 1.0, rank-0 logging every 20 batches,
per-epoch checkpoints with keep-1-best-by-loss retention.

TPU redesigns worth noting:
- The reference's filesystem data barrier (rank 0 writes _DATA_PREP_DONE,
  others poll sleep(5), pytorch_llm_ray.py:156-188) is replaced by host-0
  prep + a real collective barrier
  (multihost_utils.sync_global_devices) — no eventually-consistent-FUSE
  race (SURVEY.md §5.2).
- DDP + DistributedSampler become mesh sharding + ShardedBatches.
- Resume-from-latest-checkpoint actually works (§5.3 gap-fix).
"""

from __future__ import annotations

import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

logging.basicConfig(level=logging.INFO,
                    format="%(asctime)s %(name)s: %(message)s")
logger = logging.getLogger("pretrain")


def train_loop_per_worker(config: dict):
    import jax
    import numpy as np

    from gke_ray_train_tpu.ckpt import CheckpointManager
    from gke_ray_train_tpu.data import (
        CharTokenizer, ShardedBatches, SlidingWindowDataset,
        prepare_wikitext2)
    from gke_ray_train_tpu.models import basic_lm
    from gke_ray_train_tpu.parallel.mesh import distributed_init
    from gke_ray_train_tpu.parallel.placement import (
        input_shard_layout, make_place_batch)
    from gke_ray_train_tpu.rayint import get_context
    from gke_ray_train_tpu.train import (
        ThroughputMeter, make_optimizer, make_train_state, make_train_step,
        warmup_cosine_schedule)
    from gke_ray_train_tpu.train.loop import run_training

    ctx = get_context()
    distributed_init()
    seq_len = int(config.get("dataset_seq_len", 256))
    # ONE declarative ExecutionPlan (plan.py): env supplies the
    # guard/compile-cache knobs, the driver config supplies mesh +
    # batch shape via the kwargs dialect — identical plan (and
    # fingerprint) to the same settings spelled in the JSON dialect
    from gke_ray_train_tpu.plan import ExecutionPlan, compile_step_with_plan
    plan = ExecutionPlan.resolve(
        config={k: config[k] for k in
                ("MESH_DATA", "MESH_FSDP", "COMPILE_CACHE_DIR")
                if k in config},
        per_device_batch=int(config.get("batch_size_per_device", 16)),
        max_seq_len=seq_len,
        prefetch=int(config.get("prefetch_batches",
                                config.get("PREFETCH_BATCHES", 2))))
    # elastic mesh re-formation (rayint/elastic.py): when the trainer's
    # post-mortem shrank/grew the pool, re-resolve the plan on the
    # survivors (data/fsdp reflowed, global batch preserved) and build
    # the mesh on exactly those devices; restore below reshards from
    # the logical spec. A no-op when ELASTIC is off or the pool is full.
    # Replan BEFORE enabling the cache — the cache subdir is namespaced
    # by the plan's compile fingerprint, which must be the survivors'.
    from gke_ray_train_tpu.rayint.elastic import maybe_replan
    plan, devices = maybe_replan(plan, config=config, log=logger)
    # tuned-plan overlay (autotune/registry.py): AUTOTUNE=1 overlays a
    # registry hit AFTER the replan (the lookup keys on the attempt's
    # real topology) and BEFORE the cache/mesh. This entry's model is
    # data-derived (tokenizer vocab), so the static model-digest lookup
    # usually misses — the hook logs that loudly rather than guessing.
    from gke_ray_train_tpu.autotune.registry import maybe_apply
    plan, _ = maybe_apply(plan, config=config, log=logger)
    # persistent XLA compile cache on the shared PVC: the first worker
    # to compile pays; every restart (and every other host) reuses the
    # binary. Re-enabled here (the trainer already enabled it pre-init)
    # so the cache dir carries the real device-topology fingerprint.
    from gke_ray_train_tpu.perf.cache import enable_persistent_cache
    enable_persistent_cache(plan=plan)
    mesh = plan.build_mesh(devices)
    n_hosts = max(jax.process_count(), 1)
    host = jax.process_index()
    logger.info("worker %d/%d; mesh %s; plan %s", host, n_hosts,
                dict(mesh.shape), plan.fingerprint())

    data_dir = config.get("data_dir", "/mnt/pvc/data")
    tok_path = os.path.join(data_dir, "char_tokenizer.json")
    ids_path = os.path.join(data_dir, "wikitext2_train_ids.npy")

    # ---- host-0 data prep + collective barrier -----------------------
    if host == 0 and not (os.path.exists(tok_path)
                          and os.path.exists(ids_path)):
        paths = prepare_wikitext2(data_dir, synthetic_fallback=True)
        text = open(paths["train"]).read()
        tok = CharTokenizer.fit(text)
        tok.save(tok_path)
        np.save(ids_path, tok.encode(text))
        logger.info("data prep done: %d tokens, vocab %d",
                    os.path.getsize(ids_path) // 4, tok.vocab_size)
    if n_hosts > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("data_prep_done")

    tok = CharTokenizer.load(tok_path)
    ids = np.load(ids_path)
    dataset = SlidingWindowDataset(ids, seq_len)

    cfg = basic_lm(
        vocab_size=tok.vocab_size,
        d_model=int(config.get("d_model", 2048)),
        n_layers=int(config.get("n_layers", 24)),
        n_heads=int(config.get("n_heads", 16)),
        d_ff=int(config.get("d_ff", 8192)),
        max_seq_len=max(seq_len, int(config.get("model_max_seq_len", 1024))),
        dtype=config.get("dtype", "bfloat16"),
        remat_policy=config.get("remat_policy", "full"),
    )

    global_batch = plan.per_device_batch \
        * mesh.shape["data"] * mesh.shape["fsdp"]
    # test_run parity: cap at 16k samples (pytorch_llm_ray.py:198-201);
    # "max_samples" shrinks further for fast CI smoke
    max_samples = (int(config["max_samples"]) if "max_samples" in config
                   else (16_000 if config.get("test_run", True) else None))
    # input partitioning follows the mesh (hosts spanned by model/context
    # axes feed identical rows — parallel/placement.py)
    in_shards, in_shard_id = input_shard_layout(mesh)
    batches = ShardedBatches(
        dataset, global_batch, num_hosts=in_shards, host_id=in_shard_id,
        max_samples=max_samples)

    epochs = int(config.get("epochs", 1))
    total_steps = batches.steps_per_epoch() * epochs
    schedule = warmup_cosine_schedule(
        float(config.get("lr", 3e-4)), total_steps,
        warmup_frac=float(config.get("warmup_frac", 0.05)),
        min_lr_frac=float(config.get("min_lr_frac", 0.01)))
    opt = make_optimizer(schedule,
                         weight_decay=float(config.get("weight_decay", 0.01)),
                         clip_norm=float(config.get("grad_clip", 1.0)))
    state = make_train_state(cfg, opt, jax.random.key(0), mesh=mesh)

    step_fn = make_train_step(cfg, opt, mesh=mesh, schedule=schedule,
                              plan=plan)
    run_dir = os.path.join(
        config.get("storage_path", "/mnt/pvc/ray_llm_training_runs"),
        config.get("run_name", "basic_lm"))
    # AOT train executable beside the checkpoint (perf/cache.py),
    # under the plan's AOT policy: build once via
    # jit(...).lower(...).compile() and serialize; a preempted retry
    # deserializes it and reaches its first step without retracing.
    # Signature or plan-fingerprint drift falls back to the jitted step.
    from gke_ray_train_tpu.perf.cache import make_abstract_batch
    step_fn = compile_step_with_plan(
        plan, mesh, step_fn, state,
        make_abstract_batch(mesh, global_batch, seq_len),
        sidecar=os.path.join(run_dir, "aot_train_step.bin"),
        label="pretrain train_step")
    # recency retention, keep 2 (NOT the reference's keep-1-best): the
    # training manager exists to RESUME — best-by-loss retention would
    # garbage-collect a grace-window preemption save whose loss is not
    # among the best, and the corrupt-checkpoint fallback needs an
    # earlier restorable step to survive an interrupted latest save
    # goodput knobs: ASYNC_CKPT=1 moves the storage commit behind a
    # write-ahead marker on a background thread (the loop blocks only
    # for the device→host snapshot); PEER_REPLICATION=1 streams every
    # snapshot to the peer slice's hot store so a slice eviction
    # resumes without a storage read. Config-first with env fallback —
    # the SERVE_AFTER_TRAIN dual-read idiom.
    def _goodput_flag(key):
        return str(config.get(key, os.environ.get(key, "0"))
                   ).strip().lower() not in ("", "0", "false", "no")
    peer = None
    if _goodput_flag("PEER_REPLICATION"):
        from gke_ray_train_tpu.ckpt.peer import PeerReplicator
        peer = PeerReplicator.from_env()
    mgr = CheckpointManager(
        run_dir, max_to_keep=2, score_attribute=None,
        async_commit=_goodput_flag("ASYNC_CKPT"),
        commit_timeout_s=float(config.get(
            "CKPT_COMMIT_TIMEOUT_S",
            os.environ.get("CKPT_COMMIT_TIMEOUT_S", "120"))),
        peer=peer)
    if ctx.is_host0():
        # tokenizer beside the checkpoints: the run dir alone is enough
        # to decode/resume (reference saves the tokenizer with the
        # pre-train artifact too)
        from gke_ray_train_tpu.data import save_tokenizer
        save_tokenizer(tok, run_dir)

    meter = ThroughputMeter(cfg, seq_len=seq_len,
                            n_devices=len(devices))
    from gke_ray_train_tpu.train.profiling import profiler_from_config
    state, metrics = run_training(
        state, step_fn, lambda e: batches.iter_epoch(e),
        epochs=epochs,
        # shardlint runtime guards: TRANSFER_GUARD / DIVERGENCE_GUARD
        # (analysis/guards.py), plan-resolved (env dialect)
        guards=plan.runtime_guards(),
        # host-local rows → global sharded arrays (SURVEY.md row D9)
        place_batch=make_place_batch(
            mesh, context_sharded=mesh.shape["context"] > 1),
        # background prefetch overlaps the sliding-window slice + form-up
        # with the step (data/prefetch.py); 0 = synchronous
        prefetch=plan.prefetch,
        log_every=int(config.get("log_every", 20)),
        meter=meter, ckpt_manager=mgr,
        report_fn=lambda m: ctx.report(m),
        # step-granular liveness reports for the heartbeat supervisor
        # (rayint/supervisor.py); a no-op when no sink is wired
        heartbeat_fn=ctx.heartbeat,
        profiler=profiler_from_config(
            config, os.path.join(config.get("storage_path", "/tmp"),
                                 "profile")),
        is_host0=ctx.is_host0())

    # ---- optional post-train serving smoke (serve/, ROADMAP #2) ------
    # the just-pretrained LM serves a few continuations through the
    # continuous-batching engine — train → serve on the same process.
    # Single-host only (multi-host serves via rayint/serving.py).
    serve_flag = config.get("SERVE_AFTER_TRAIN",
                            os.environ.get("SERVE_AFTER_TRAIN", "0"))
    if str(serve_flag).strip().lower() in ("1", "true"):
        if n_hosts > 1:
            logger.warning("SERVE_AFTER_TRAIN is single-host only; "
                           "skipping")
        else:
            from gke_ray_train_tpu.serve import post_train_smoke
            # a few sliding-window prefixes of the training corpus;
            # no adapter_ids — pretraining trains the FULL weights, so
            # there is no adapter to tag (the fine-tune entry tags its
            # smoke with the trained LoRA and serves via AdapterPool)
            prompts = [ids[i * 257:i * 257 + 48] for i in range(4)]
            out = post_train_smoke(state.params, cfg, plan, prompts,
                                   max_new_tokens=48)
            if out is not None and ctx.is_host0():
                comps, stats = out
                for c in comps:
                    logger.info("serve smoke %s: %r", c.rid,
                                tok.decode(np.asarray(c.generated)))
                ctx.report({**metrics, "serve_smoke": stats})
    # obs: record the run's durable artifact (checkpoints + tokenizer
    # dir) as an event; the obs dir itself defaults to
    # <storage_path>/<run_name>/obs for this entry (obs/runtime.py)
    from gke_ray_train_tpu.obs import runtime as obs_runtime
    obs_runtime.emit("export", path=run_dir, what="checkpoint")
    return metrics


if __name__ == "__main__":
    from gke_ray_train_tpu.rayint import JaxTrainer, RunConfig, ScalingConfig
    from gke_ray_train_tpu.rayint.trainer import FailureConfig

    # hardcoded driver config, reference-style (pytorch_llm_ray.py:324-344),
    # with env overrides for smoke runs
    smoke = os.environ.get("SMOKE_TEST", "0") == "1"
    train_loop_config = {
        "d_model": 256 if smoke else 2048,
        "n_layers": 4 if smoke else 24,
        "n_heads": 8 if smoke else 16,
        "d_ff": 1024 if smoke else 8192,
        "dataset_seq_len": 128 if smoke else 256,
        "model_max_seq_len": 1024,
        "batch_size_per_device": 4 if smoke else 16,
        "lr": 3e-4, "weight_decay": 0.01,
        "warmup_frac": 0.05, "min_lr_frac": 0.01, "grad_clip": 1.0,
        "epochs": 1,
        "test_run": True,
        **({"max_samples": int(os.environ.get("MAX_SAMPLES", "1600"))}
           if smoke else {}),
        "log_every": 20,
        "prefetch_batches": int(os.environ.get("PREFETCH_BATCHES", "2")),
        "dtype": "float32" if smoke else "bfloat16",
        "data_dir": os.environ.get("DATA_DIR", "/mnt/pvc/data"),
        "storage_path": os.environ.get(
            "STORAGE_PATH", "/mnt/pvc/ray_llm_training_runs"),
        "run_name": "basic_lm_pretrain",
        "MESH_FSDP": int(os.environ.get("MESH_FSDP", "-1")),
        "MESH_DATA": int(os.environ.get("MESH_DATA", "1")),
    }
    trainer = JaxTrainer(
        train_loop_per_worker,
        train_loop_config=train_loop_config,
        scaling_config=ScalingConfig.from_env(),
        run_config=RunConfig(
            name="basic-lm-pretrain",
            storage_path=train_loop_config["storage_path"],
            # fault-tolerance knobs (README "Fault tolerance",
            # ray-jobs/README.md): failures vs preemptions are budgeted
            # separately — a spot eviction must not burn a retry slot
            failure_config=FailureConfig(
                max_failures=int(os.environ.get("MAX_FAILURES", "0")),
                max_preemptions=int(
                    os.environ.get("MAX_PREEMPTIONS", "8"))),
            # hang detection (rayint/trainer.py): unset = wait forever
            worker_timeout_s=(float(os.environ["WORKER_TIMEOUT_S"])
                              if "WORKER_TIMEOUT_S" in os.environ
                              else None),
            # step-granular supervision (rayint/supervisor.py)
            heartbeat_timeout_s=(float(os.environ["HEARTBEAT_TIMEOUT_S"])
                                 if "HEARTBEAT_TIMEOUT_S" in os.environ
                                 else None)),
    )
    result = trainer.fit()
    if result.error:
        logger.error("training %s after %d attempt(s) "
                     "(%d preemption(s)): %s", result.status,
                     result.attempts, result.preemptions, result.error)
        sys.exit(1)
    logger.info("final metrics: %s (attempts=%d preemptions=%d)",
                result.metrics, result.attempts, result.preemptions)
    # unified telemetry (obs/): the one merged per-run view
    from gke_ray_train_tpu.obs.runtime import resolve_obs_dir
    _obs_dir = resolve_obs_dir(None, train_loop_config)
    if _obs_dir is not None:
        logger.info("run telemetry: python -m gke_ray_train_tpu.obs "
                    "report %s --text", _obs_dir)
